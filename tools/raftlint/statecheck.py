"""Statecheck analysis core (raftlint 4.0): the two state surfaces the
live-serving roadmap churns hardest — compiled-program cache keys and
checkpoint schemas — reduced to machine-checkable dataflow questions.

Memoized-trace sites (``_cached_wrapper`` callers, module-level
``*_CACHE`` dict caches) build a jitted/shard_map'd program from the
names their build closure READS; the cache key must cover every one of
those reads or a stale compiled program silently serves after the input
changes (the PR-1 fault-plan, PR-4 probe-count, PR-12 adaptive-flag bug
class). This module answers, per site:

  - which enclosing-scope names the build closure (transitively, through
    sibling nested defs it references) actually reads — its **trace
    inputs**;
  - which of those **flow into the key**: the name appears in the key
    expression, or every reaching assignment derives it from key-covered
    names, module-level statics, and function-scope imports (a bounded
    derivation fixpoint). Derivations through a **tuned read**
    (``tuned.get``/``get_choice``/``hints``, directly or via a resolved
    callee's summary) never count as covered — tuned state is
    process-global but NOT process-stable, exactly why
    ``resolve_setup_impls`` results are keyed at every site.

Checkpoint sites (``serialize_arrays``/``_write_ckpt`` callers, the
``load``/``ivf_*_load`` dispatchers) are matched against the
machine-readable ``core/serialize.py::CKPT_SCHEMA`` registry — read by
AST here, never by import (raft_tpu would drag jax in). The extraction
helpers resolve dict-literal keys through local name chasing,
``**splat`` helper calls, and ONE level of save-helper parameterization
(``_save_local_impl(filename, index, store, kind, quant_arrays, meta)``
resolves at each caller), failing CLOSED on anything murkier.

Everything is stdlib ``ast``, deterministic (sorted iteration
throughout), and under-reports rather than guessing — except where a
registry entry exists, which must never turn the gate green unverified.
"""

from __future__ import annotations

import ast
import builtins
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.raftlint.engine import (
    Module,
    const_str,
    dotted_chain,
    load_module,
    terminal_name,
)
from tools.raftlint.project import ProjectIndex, is_tuned_read

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

_BUILTINS = frozenset(dir(builtins))

#: the memoized-trace entry point the MNMG serving layer routes through
CACHED_WRAPPER_NAMES = frozenset({"_cached_wrapper"})

#: the shared key constructor (mnmg_common.wrapper_key): its args ARE
#: the key parts, and the comms session argument covers the mesh/axis
WRAPPER_KEY_NAMES = frozenset({"wrapper_key"})

CKPT_REGISTRY_RELPATH = "raft_tpu/core/serialize.py"

#: the integrity sidecar's field registry (raft_tpu.integrity.digest.
#: DIGEST_FIELDS) — AST-read like CKPT_SCHEMA, pinned against it by the
#: integrity-digest-registry rule
DIGEST_REGISTRY_RELPATH = "raft_tpu/integrity/digest.py"

#: writers whose (arrays, meta) arguments define a checkpoint's on-disk
#: field set (positional layout ``writer(file, arrays, meta)``)
CKPT_WRITER_NAMES = frozenset({"serialize_arrays", "_write_ckpt"})

#: the schema-gated read entry points a load path must route through
CKPT_GATE_NAMES = frozenset({"read_ckpt", "check_ckpt_version"})

#: `<param> + "_part"` checkpoint kinds share one part-file schema
PART_SCHEMA_KIND = "mnmg_sharded_part"


# -- scope-aware free variables -----------------------------------------

def _bound_in(fn: ast.AST) -> Set[str]:
    """Names bound directly in `fn`'s scope: params, assignment/for/with
    targets, walrus targets, imports, nested def/class names —
    comprehension targets included (their leakage is a Python-2-ism we
    deliberately over-bind against). Does not descend into nested defs."""
    out: Set[str] = set()
    if isinstance(fn, _FUNCS + (ast.Lambda,)):
        a = fn.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            out.add(p.arg)
        if a.vararg:
            out.add(a.vararg.arg)
        if a.kwarg:
            out.add(a.kwarg.arg)
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNCS):
            out.add(node.name)
            continue  # its body is its own scope
        if isinstance(node, ast.Lambda):
            continue
        if isinstance(node, ast.ClassDef):
            out.add(node.name)
            continue
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            out.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                if alias.name != "*":
                    out.add(alias.asname
                            or alias.name.split(".")[0])
        elif isinstance(node, ast.ExceptHandler) and node.name:
            out.add(node.name)
        stack.extend(ast.iter_child_nodes(node))
    return out


def free_names(fn: ast.AST) -> Set[str]:
    """Names `fn` (or a scope nested inside it) reads from enclosing
    scopes — the closure's input surface. Scope-accurate per nesting
    level; over-binds comprehension targets (under-reporting, by
    design)."""
    bound = _bound_in(fn)
    free: Set[str] = set()
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNCS + (ast.Lambda,)):
            free |= free_names(node) - bound
            continue
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id not in bound and node.id not in _BUILTINS:
                free.add(node.id)
        stack.extend(ast.iter_child_nodes(node))
    return free


def _import_bound(fn: ast.AST) -> Set[str]:
    """Names bound by import statements anywhere inside `fn` (function-
    scope imports resolve to fixed module attributes — static, like
    module-level names)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                if alias.name != "*":
                    out.add(alias.asname or alias.name.split(".")[0])
    return out


def module_static_names(module: Module) -> Set[str]:
    """Module-level bindings: imports, top-level defs/classes, and
    module constants. Process-stable from a trace-cache perspective
    (the one mutable exception — the tuned registry — is handled by the
    tuned-read taint, not here)."""
    out: Set[str] = set()
    for node in module.tree.body:
        if isinstance(node, _FUNCS) or isinstance(node, ast.ClassDef):
            out.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                if alias.name != "*":
                    out.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        if isinstance(e, ast.Name):
                            out.add(e.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name):
            out.add(node.target.id)
    return out


# -- key expressions and derivation coverage ----------------------------

def key_expr_names(key: ast.AST) -> Optional[Set[str]]:
    """Every Name read anywhere inside the key expression (attribute
    roots included: ``comms.mesh`` covers ``comms``); None when the
    expression is not an analyzable key shape (not a tuple literal or a
    ``wrapper_key(...)`` call)."""
    if isinstance(key, ast.Call) and terminal_name(
            key.func) in WRAPPER_KEY_NAMES:
        names: Set[str] = set()
        for a in list(key.args) + [kw.value for kw in key.keywords]:
            for n in ast.walk(a):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                    names.add(n.id)
        return names
    if isinstance(key, ast.Tuple):
        names = set()
        for n in ast.walk(key):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                names.add(n.id)
        return names
    return None


def key_tag(key: ast.AST) -> Optional[str]:
    """The site's const tag (first key element), for messages."""
    elts = ()
    if isinstance(key, ast.Call) and terminal_name(
            key.func) in WRAPPER_KEY_NAMES:
        elts = key.args
    elif isinstance(key, ast.Tuple):
        elts = key.elts
    return const_str(elts[0]) if elts else None


def _assignments_in(fns: Sequence[ast.AST]) -> Dict[str, List[ast.AST]]:
    """name -> RHS expressions assigned to it across the enclosing
    function chain (pairwise for same-length tuple-to-tuple assigns, the
    ``impl, cb = _search_impl, None`` idiom; whole-RHS otherwise).
    Nested defs are skipped — their assignments are their own scope."""
    out: Dict[str, List[ast.AST]] = {}

    def add(target: ast.AST, value: ast.AST) -> None:
        if isinstance(target, ast.Name):
            out.setdefault(target.id, []).append(value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) \
                    and len(value.elts) == len(target.elts):
                for t, v in zip(target.elts, value.elts):
                    add(t, v)
            else:
                for t in target.elts:
                    add(t, value)

    for fn in fns:
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNCS + (ast.Lambda,)):
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    add(t, node.value)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if node.value is not None:
                    add(node.target, node.value)
            elif isinstance(node, ast.NamedExpr):
                add(node.target, node.value)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                add(node.target, node.iter)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        add(item.optional_vars, item.context_expr)
            stack.extend(ast.iter_child_nodes(node))
    return out


def _rhs_tuned(expr: ast.AST, index: Optional[ProjectIndex],
               module_path: str) -> bool:
    """Does this RHS (transitively, via resolved callee summaries) read
    the tuned registry? Tuned-tainted derivations are never 'covered' —
    a mid-process tuned flip must rebuild the wrapper."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        if is_tuned_read(node):
            return True
        if index is not None:
            for q in index.resolve_call(module_path, node.func):
                s = index.summaries.get(q)
                if s is not None and s.tuned_read:
                    return True
    return False


@dataclasses.dataclass
class CoverageEnv:
    """The derivation context of one memoized site: the enclosing
    function chain's assignments, the static name sets, and the project
    index for tuned-read resolution."""

    assigns: Dict[str, List[ast.AST]]
    static: Set[str]  # module-level + function-scope-import names
    module_path: str
    index: Optional[ProjectIndex] = None

    def covered_closure(self, seed: Set[str], bound: int = 64) -> Set[str]:
        """Expand key-covered names through derivations: a name joins
        when EVERY reaching assignment's free reads are covered/static
        and tuned-free. Bounded fixpoint, deterministic order."""
        covered = set(seed)
        for _ in range(bound):
            grew = False
            for name in sorted(self.assigns):
                if name in covered:
                    continue
                rhss = self.assigns[name]
                if not rhss:
                    continue
                ok = True
                for rhs in rhss:
                    if _rhs_tuned(rhs, self.index, self.module_path):
                        ok = False
                        break
                    for n in ast.walk(rhs):
                        if isinstance(n, ast.Name) \
                                and isinstance(n.ctx, ast.Load) \
                                and n.id != name \
                                and n.id not in covered \
                                and n.id not in self.static \
                                and n.id not in _BUILTINS:
                            ok = False
                            break
                    if not ok:
                        break
                if ok:
                    covered.add(name)
                    grew = True
            if not grew:
                break
        return covered


# -- memoized-trace site extraction -------------------------------------

@dataclasses.dataclass
class CacheSite:
    """One ``_cached_wrapper(key, build)`` call: the key expression, the
    resolved build def (or None), and the enclosing function chain."""

    module: Module
    call: ast.Call
    key: ast.AST
    build: Optional[ast.AST]
    chain: List[ast.AST]  # enclosing functions, outermost first


def _function_chains(module: Module):
    """Yield (fn, chain) for every def at any depth; `chain` is the
    enclosing function list ending with fn itself."""

    def walk(node, chain):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNCS):
                yield child, chain + [child]
                yield from walk(child, chain + [child])
            elif not isinstance(child, ast.Lambda):
                yield from walk(child, chain)

    yield from walk(module.tree, [])


def collect_cache_sites(module: Module) -> List[CacheSite]:
    sites: List[CacheSite] = []
    for fn, chain in _function_chains(module):
        # only this function's OWN statements (a site inside a nested
        # def is found when that def is visited)
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNCS + (ast.Lambda,)):
                continue
            if isinstance(node, ast.Call) and terminal_name(
                    node.func) in CACHED_WRAPPER_NAMES and len(node.args) >= 2:
                key, build_ref = node.args[0], node.args[1]
                build = _resolve_build(build_ref, chain)
                sites.append(CacheSite(module, node, key, build, chain))
            stack.extend(ast.iter_child_nodes(node))
    # the wrapper's own definition passes its `key` param through — it
    # is the mechanism, not a site
    return [s for s in sites
            if not (s.chain and s.chain[-1].name in CACHED_WRAPPER_NAMES)]


def _resolve_build(ref: ast.AST, chain: Sequence[ast.AST]) -> Optional[ast.AST]:
    if isinstance(ref, ast.Lambda):
        return ref
    if not isinstance(ref, ast.Name):
        return None
    for fn in reversed(list(chain)):
        for node in ast.walk(fn):
            if isinstance(node, _FUNCS) and node.name == ref.id:
                return node
    return None


def local_fn_defs(chain: Sequence[ast.AST]) -> Dict[str, ast.AST]:
    """Function defs visible in the enclosing chain's scopes (sibling
    helpers like ``finish`` — a build referencing one inherits its free
    reads)."""
    out: Dict[str, ast.AST] = {}
    for fn in chain:
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNCS):
                out.setdefault(node.name, node)
                continue
            if not isinstance(node, ast.Lambda):
                stack.extend(ast.iter_child_nodes(node))
    return out


def trace_inputs(build: ast.AST, chain: Sequence[ast.AST],
                 static: Set[str]) -> Set[str]:
    """The build closure's enclosing-scope reads, expanded transitively
    through sibling nested defs it references (``finish`` et al.), minus
    statics — the names that must flow into the key."""
    helpers = local_fn_defs(chain)
    seen_fns: Set[int] = set()
    names: Set[str] = set()
    queue = [build]
    while queue:
        fn = queue.pop()
        if id(fn) in seen_fns:
            continue
        seen_fns.add(id(fn))
        for name in sorted(free_names(fn)):
            helper = helpers.get(name)
            if helper is not None and helper is not fn:
                queue.append(helper)
                continue
            names.add(name)
    own_bound: Set[str] = set()
    for fn in chain:
        own_bound |= _import_bound(fn)
    return {n for n in names if n not in static and n not in own_bound
            and n not in _BUILTINS}


def tuned_reads_inside(fn: ast.AST) -> List[ast.Call]:
    """Direct tuned-registry reads INSIDE a build closure: the traced
    program would bake one read of mutable global state without keying
    it."""
    return [node for node in ast.walk(fn)
            if isinstance(node, ast.Call) and is_tuned_read(node)]


# -- module-level *_CACHE dict sites ------------------------------------

@dataclasses.dataclass
class DictCacheSite:
    module: Module
    fn: ast.AST
    cache_name: str
    key: ast.AST           # resolved tuple expression
    key_node: ast.AST      # where to anchor findings
    value_exprs: List[ast.AST]  # RHS of `CACHE[key] = v` stores


def module_cache_names(module: Module) -> Set[str]:
    return {n for n in module_static_names(module) if n.endswith("_CACHE")}


def collect_dict_cache_sites(module: Module) -> List[DictCacheSite]:
    caches = module_cache_names(module)
    if not caches:
        return []
    sites: List[DictCacheSite] = []
    for fn, chain in _function_chains(module):
        params = set()
        if isinstance(fn, _FUNCS):
            a = fn.args
            params = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
        assigns = _assignments_in([fn])
        key_expr: Optional[ast.AST] = None
        key_node: Optional[ast.AST] = None
        cache_name = ""
        values: List[ast.AST] = []
        opaque = False
        for node in ast.walk(fn):
            k = None
            cname = ""
            if isinstance(node, ast.Subscript) and isinstance(
                    node.value, ast.Name) and node.value.id in caches:
                k = node.slice
                cname = node.value.id
            elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and isinstance(
                    node.func.value, ast.Name) \
                    and node.func.value.id in caches \
                    and node.func.attr in ("get", "setdefault", "pop") \
                    and node.args:
                k = node.args[0]
                cname = node.func.value.id
            if k is None:
                continue
            expr = k
            if isinstance(k, ast.Name):
                if k.id in params:
                    opaque = True  # the wrapper mechanism: key is opaque
                    continue
                rhss = assigns.get(k.id, [])
                expr = rhss[0] if len(rhss) == 1 else None
            if isinstance(expr, ast.Tuple) and (
                    key_node is None
                    or (k.lineno, k.col_offset)
                    < (key_node.lineno, key_node.col_offset)):
                key_expr, key_node = expr, k  # earliest usage anchors
                cache_name = cname
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and isinstance(
                            t.value, ast.Name) and t.value.id in caches:
                        values.append(node.value)
        if key_expr is not None and not opaque and values:
            sites.append(DictCacheSite(module, fn, cache_name, key_expr,
                                       key_node, values))
    return sites


# -- checkpoint schema registry (AST-read) ------------------------------

@dataclasses.dataclass(frozen=True)
class FieldSpec:
    category: str   # array | meta | runtime
    dtype: Optional[str]
    since: int
    absent: str     # refuse | default | derive
    line: int
    col: int


@dataclasses.dataclass
class KindSchema:
    version: int
    fields: Dict[str, FieldSpec]
    line: int
    col: int


def load_ckpt_schema(modules: Sequence[Module], repo_root: str
                     ) -> Tuple[Optional[Dict[str, KindSchema]], Optional[str]]:
    """Parse ``CKPT_SCHEMA`` from core/serialize.py (scanned set first,
    disk fallback). None when missing or not a literal — fail closed."""
    reg_mod = next((m for m in modules if m.path == CKPT_REGISTRY_RELPATH),
                   None)
    if reg_mod is None:
        import os

        abspath = os.path.join(repo_root, CKPT_REGISTRY_RELPATH)
        if os.path.exists(abspath):
            reg_mod, _err = load_module(abspath, repo_root)
    if reg_mod is None:
        return None, None
    for node in ast.walk(reg_mod.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "CKPT_SCHEMA"
                for t in node.targets):
            schema = _parse_schema(node.value)
            return schema, reg_mod.path
    return None, reg_mod.path


def _parse_schema(node: ast.AST) -> Optional[Dict[str, KindSchema]]:
    if not isinstance(node, ast.Dict):
        return None
    out: Dict[str, KindSchema] = {}
    for k, v in zip(node.keys, node.values):
        kind = const_str(k)
        if kind is None or not isinstance(v, ast.Dict):
            return None
        version = None
        fields: Dict[str, FieldSpec] = {}
        for kk, vv in zip(v.keys, v.values):
            key = const_str(kk)
            if key == "version" and isinstance(vv, ast.Constant) \
                    and isinstance(vv.value, int):
                version = vv.value
            elif key == "fields" and isinstance(vv, ast.Dict):
                for fk, fv in zip(vv.keys, vv.values):
                    fname = const_str(fk)
                    spec = _parse_field(fv, fk)
                    if fname is None or spec is None:
                        return None
                    fields[fname] = spec
        if version is None:
            return None
        out[kind] = KindSchema(version, fields, k.lineno, k.col_offset + 1)
    return out


def _parse_field(node: ast.AST, key_node: ast.AST) -> Optional[FieldSpec]:
    if not isinstance(node, ast.Tuple) or len(node.elts) != 4:
        return None
    vals = []
    for e in node.elts:
        if isinstance(e, ast.Constant):
            vals.append(e.value)
        else:
            return None
    cat, dtype, since, absent = vals
    if cat not in ("array", "meta", "runtime") \
            or absent not in ("refuse", "default", "derive") \
            or not isinstance(since, int):
        return None
    return FieldSpec(cat, dtype, since, absent,
                     key_node.lineno, key_node.col_offset + 1)


@dataclasses.dataclass(frozen=True)
class DigestSpec:
    granularity: str  # list | table
    line: int
    col: int


def load_digest_fields(modules: Sequence[Module], repo_root: str
                       ) -> Tuple[Optional[Dict[str, Dict[str, DigestSpec]]],
                                  Optional[str]]:
    """Parse ``DIGEST_FIELDS`` from integrity/digest.py (scanned set
    first, disk fallback) into kind -> {field -> DigestSpec}. None when
    missing, not a literal, or a granularity is not list/table — the
    digest-registry rule fails closed on None exactly like the
    checkpoint-schema rule does."""
    reg_mod = next((m for m in modules if m.path == DIGEST_REGISTRY_RELPATH),
                   None)
    if reg_mod is None:
        import os

        abspath = os.path.join(repo_root, DIGEST_REGISTRY_RELPATH)
        if os.path.exists(abspath):
            reg_mod, _err = load_module(abspath, repo_root)
    if reg_mod is None:
        return None, None
    for node in ast.walk(reg_mod.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "DIGEST_FIELDS"
                for t in node.targets):
            return _parse_digest_fields(node.value), reg_mod.path
    return None, reg_mod.path


def _parse_digest_fields(node: ast.AST
                         ) -> Optional[Dict[str, Dict[str, DigestSpec]]]:
    if not isinstance(node, ast.Dict):
        return None
    out: Dict[str, Dict[str, DigestSpec]] = {}
    for k, v in zip(node.keys, node.values):
        kind = const_str(k)
        if kind is None or not isinstance(v, ast.Dict):
            return None
        fields: Dict[str, DigestSpec] = {}
        for fk, fv in zip(v.keys, v.values):
            fname = const_str(fk)
            gran = const_str(fv)
            if fname is None or gran not in ("list", "table"):
                return None
            fields[fname] = DigestSpec(gran, fk.lineno, fk.col_offset + 1)
        out[kind] = fields
    return out


# -- checkpoint save-site extraction ------------------------------------

@dataclasses.dataclass
class SaveSite:
    module: Module
    node: ast.Call
    kind: Optional[str]               # resolved kind, or None
    array_keys: List[Tuple[str, ast.AST]]
    meta_keys: List[Tuple[str, ast.AST]]
    unresolved: List[Tuple[str, ast.AST]]  # human tag + anchor, fail closed


def _writer_args(call: ast.Call) -> Tuple[Optional[ast.AST], Optional[ast.AST]]:
    """(arrays, meta) of a ``writer(file, arrays, meta)`` call."""
    arrays = call.args[1] if len(call.args) > 1 else None
    meta = call.args[2] if len(call.args) > 2 else None
    for kw in call.keywords:
        if kw.arg == "arrays":
            arrays = kw.value
        elif kw.arg == "meta":
            meta = kw.value
    return arrays, meta


class _SaveResolver:
    """Resolves a writer call's arrays/meta expressions to const field
    keys within one function, chasing local names, ``**splat`` helpers,
    and (via `param_env`) one level of caller-supplied parameter
    values."""

    def __init__(self, module: Module, fn: ast.AST, index: ProjectIndex,
                 param_env: Optional[Dict[str, ast.AST]] = None,
                 caller: Optional["_SaveResolver"] = None):
        self.module = module
        self.fn = fn
        self.index = index
        self.assigns = _assignments_in([fn])
        self.params = set()
        if isinstance(fn, _FUNCS):
            a = fn.args
            self.params = {p.arg for p in
                           a.posonlyargs + a.args + a.kwonlyargs}
        self.param_env = param_env or {}
        self.caller = caller

    def dict_keys(self, expr: ast.AST, depth: int = 0
                  ) -> Tuple[List[Tuple[str, ast.AST]],
                             List[Tuple[str, ast.AST]]]:
        """(resolved const keys, unresolved tags) of a dict-valued
        expression."""
        keys: List[Tuple[str, ast.AST]] = []
        bad: List[Tuple[str, ast.AST]] = []
        if expr is None:
            return keys, bad
        if depth > 4:
            return keys, [("dict resolution too deep", expr)]
        if isinstance(expr, ast.Dict):
            for k, v in zip(expr.keys, expr.values):
                if k is None:  # **splat
                    sk, sb = self._splat_keys(v, depth)
                    keys += sk
                    bad += sb
                    continue
                s = const_str(k)
                if s is None:
                    bad.append(("non-const dict key", k))
                else:
                    keys.append((s, k))
            return keys, bad
        if isinstance(expr, ast.Name):
            if expr.id in self.params:
                bound = self.param_env.get(expr.id)
                if bound is not None and self.caller is not None:
                    return self.caller.dict_keys(bound, depth + 1)
                return [], [("parameterized dict "
                             f"{expr.id!r} with no caller binding", expr)]
            rhss = self.assigns.get(expr.id, [])
            if not rhss:
                return [], [(f"unresolvable name {expr.id!r}", expr)]
            for rhs in rhss:
                sk, sb = self.dict_keys(rhs, depth + 1)
                keys += sk
                bad += sb
            # plus `name["k"] = v` stores anywhere in the function
            for node in ast.walk(self.fn):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript) and isinstance(
                                t.value, ast.Name) \
                                and t.value.id == expr.id:
                            s = const_str(t.slice)
                            if s is None:
                                bad.append(("non-const store key", t))
                            else:
                                keys.append((s, t))
            return keys, bad
        if isinstance(expr, ast.Call):
            return self._splat_keys(expr, depth)
        return [], [("unanalyzable dict expression", expr)]

    def _splat_keys(self, expr: ast.AST, depth: int
                    ) -> Tuple[List[Tuple[str, ast.AST]],
                               List[Tuple[str, ast.AST]]]:
        """Const keys contributed by ``**helper(...)`` /
        ``**obj.method()``: resolve the callee and collect the dict-
        literal keys + const subscript stores in its body."""
        if isinstance(expr, ast.Name):
            return self.dict_keys(expr, depth + 1)
        if not isinstance(expr, ast.Call):
            return [], [("unanalyzable **splat", expr)]
        target = self._resolve_callee(expr)
        if target is None:
            return [], [("unresolvable **splat callee", expr)]
        keys: List[Tuple[str, ast.AST]] = []
        bad: List[Tuple[str, ast.AST]] = []
        found_dict = False
        for node in ast.walk(target):
            if isinstance(node, ast.Dict):
                found_dict = True  # an empty literal is a resolved answer
                for k in node.keys:
                    if k is None:
                        continue
                    s = const_str(k)
                    if s is not None:
                        keys.append((s, expr))
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        s = const_str(t.slice)
                        if s is not None:
                            keys.append((s, expr))
        if not keys and not found_dict:
            bad.append(("**splat callee writes no const keys", expr))
        return keys, bad

    def _resolve_callee(self, call: ast.Call) -> Optional[ast.AST]:
        qnames = self.index.resolve_call(self.module.path, call.func)
        if len(qnames) == 1:
            return self.index.functions[qnames[0]].node
        # `obj.method()` where obj's local assignment names its class:
        # `quant = RabitqQuantizer(...)` -> RabitqQuantizer.state_arrays
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            for rhs in self.assigns.get(f.value.id, []):
                if isinstance(rhs, ast.Call):
                    cls_name = terminal_name(rhs.func)
                    for cq, info in sorted(self.index.classes.items()):
                        if info.name == cls_name and f.attr in info.methods:
                            return info.methods[f.attr]
        hits = self.index.resolve_methods_by_name(
            terminal_name(f) or "")
        if len(hits) == 1:
            return self.index.functions[hits[0]].node
        return None

    def kind_of(self, meta_expr: ast.AST, depth: int = 0
                ) -> Tuple[Optional[str], Optional[str]]:
        """(kind, unresolved-reason) from a meta expression's "kind"
        entry. ``<param> + "_part"`` maps to the shared part schema."""
        if depth > 4:
            return None, "kind resolution too deep"
        expr = meta_expr
        if isinstance(expr, ast.Name) and expr.id not in self.params:
            rhss = self.assigns.get(expr.id, [])
            if len(rhss) == 1:
                return self.kind_of(rhss[0], depth + 1)
        if isinstance(expr, ast.Name) and expr.id in self.params:
            bound = self.param_env.get(expr.id)
            if bound is not None and self.caller is not None:
                return self.caller.kind_of(bound, depth + 1)
            return None, f"parameterized meta {expr.id!r}"
        if not isinstance(expr, ast.Dict):
            return None, "meta is not a dict literal"
        for k, v in zip(expr.keys, expr.values):
            if k is not None and const_str(k) == "kind":
                return self._kind_value(v)
        return None, None  # kind-less container: not a checkpoint

    def _kind_value(self, v: ast.AST) -> Tuple[Optional[str], Optional[str]]:
        s = const_str(v)
        if s is not None:
            return s, None
        if isinstance(v, ast.BinOp) and isinstance(v.op, ast.Add) \
                and const_str(v.right) == "_part":
            return PART_SCHEMA_KIND, None
        if isinstance(v, ast.Name):
            if v.id in self.params:
                bound = self.param_env.get(v.id)
                if bound is not None and self.caller is not None:
                    return self.caller._kind_value(bound)
                return None, f"parameterized kind {v.id!r}"
            rhss = self.assigns.get(v.id, [])
            if len(rhss) == 1:
                return self._kind_value(rhss[0])
        return None, "unresolvable kind value"


def _bind_call_params(callee: ast.AST, call: ast.Call) -> Dict[str, ast.AST]:
    """param name -> argument expression for one project call site."""
    env: Dict[str, ast.AST] = {}
    if not isinstance(callee, _FUNCS):
        return env
    a = callee.args
    names = [p.arg for p in a.posonlyargs + a.args]
    for i, arg in enumerate(call.args):
        if i < len(names):
            env[names[i]] = arg
    for kw in call.keywords:
        if kw.arg:
            env[kw.arg] = kw.value
    return env


def collect_save_sites(modules: Sequence[Module],
                       index: ProjectIndex) -> List[SaveSite]:
    """Every checkpoint write in raft_tpu/: direct writer calls resolved
    in place; parameterized helper writes (``_save_local_impl``)
    resolved once per project caller."""
    sites: List[SaveSite] = []
    by_path = {m.path: m for m in modules}
    for module in sorted(by_path.values(), key=lambda m: m.path):
        if not module.path.startswith("raft_tpu/"):
            continue
        for fn, chain in _function_chains(module):
            if isinstance(fn, _FUNCS) and fn.name in CKPT_WRITER_NAMES:
                continue  # the writers' own bodies are the mechanism
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or terminal_name(
                        node.func) not in CKPT_WRITER_NAMES:
                    continue
                arrays_e, meta_e = _writer_args(node)
                res = _SaveResolver(module, fn, index)
                kind, kind_bad = res.kind_of(meta_e) if meta_e is not None \
                    else (None, "no meta argument")
                if kind is None and kind_bad is None:
                    continue  # kind-less: a generic container, not a ckpt
                needs_caller = (kind_bad or "").startswith("parameterized")
                pdicts = [
                    e for e in (arrays_e, meta_e)
                    if isinstance(e, ast.Name) and e.id in res.params
                ]
                if needs_caller or pdicts:
                    sites += _resolve_via_callers(
                        module, fn, node, index, by_path)
                    continue
                a_keys, a_bad = res.dict_keys(arrays_e) \
                    if arrays_e is not None else ([], [])
                m_keys, m_bad = res.dict_keys(meta_e) \
                    if meta_e is not None else ([], [])
                unresolved = list(a_bad) + list(m_bad)
                if kind is None:
                    unresolved.append((kind_bad, node))
                sites.append(SaveSite(module, node, kind, a_keys, m_keys,
                                      unresolved))
    return sites


def _resolve_via_callers(module: Module, fn: ast.AST, writer_call: ast.Call,
                         index: ProjectIndex, by_path) -> List[SaveSite]:
    """One level of save-helper parameterization: re-resolve this
    writer call once per project caller of `fn`, with the caller's
    argument expressions bound to `fn`'s params."""
    qname = f"{module.path}::{fn.name}"
    out: List[SaveSite] = []
    found_caller = False
    for mpath in sorted(by_path):
        caller_mod = by_path[mpath]
        if not mpath.startswith("raft_tpu/"):
            continue
        for cfn, _chain in _function_chains(caller_mod):
            for node in ast.walk(cfn):
                if not isinstance(node, ast.Call):
                    continue
                if qname not in index.resolve_call(mpath, node.func):
                    continue
                found_caller = True
                caller_res = _SaveResolver(caller_mod, cfn, index)
                env = _bind_call_params(fn, node)
                res = _SaveResolver(module, fn, index, param_env=env,
                                    caller=caller_res)
                arrays_e, meta_e = _writer_args(writer_call)
                kind, kind_bad = res.kind_of(meta_e) \
                    if meta_e is not None else (None, "no meta argument")
                if kind is None and kind_bad is None:
                    continue
                a_keys, a_bad = res.dict_keys(arrays_e) \
                    if arrays_e is not None else ([], [])
                m_keys, m_bad = res.dict_keys(meta_e) \
                    if meta_e is not None else ([], [])
                unresolved = list(a_bad) + list(m_bad)
                if kind is None:
                    unresolved.append((kind_bad, writer_call))
                # anchor findings at the CALLER (the kind owner)
                out.append(SaveSite(caller_mod, node, kind, a_keys, m_keys,
                                    unresolved))
    if not found_caller:
        out.append(SaveSite(module, writer_call, None, [], [],
                            [("parameterized checkpoint write with no "
                              "resolvable caller", writer_call)]))
    return out


# -- checkpoint load-site extraction ------------------------------------

@dataclasses.dataclass
class FieldAccess:
    field: str
    guarded: bool   # .get(...) or an `in`-membership test
    node: ast.AST


@dataclasses.dataclass
class LoadSite:
    module: Module
    fn: ast.AST
    kinds: List[str]           # const kinds this load dispatches on
    accesses: List[FieldAccess]
    helper_accesses: List[FieldAccess]  # via resolved callees (1 level)
    calls_gate: bool           # transitively reaches read_ckpt/check_*


def _field_accesses(fn: ast.AST) -> List[FieldAccess]:
    out: List[FieldAccess] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load):
            s = const_str(node.slice)
            if s is not None:
                out.append(FieldAccess(s, False, node))
        elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and node.func.attr == "get" \
                and node.args:
            s = const_str(node.args[0])
            if s is not None:
                out.append(FieldAccess(s, True, node))
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)):
            s = const_str(node.left)
            if s is not None:
                out.append(FieldAccess(s, True, node))
    return out


def _load_kinds(fn: ast.AST) -> List[str]:
    """Const kinds a function dispatches on: ``meta.get("kind") ==
    "x"`` / ``!=`` comparisons, and ``read_ckpt(f, "x")`` calls."""
    kinds: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
            sides = [node.left, node.comparators[0]]
            consts = [const_str(s) for s in sides]
            for side, other in ((0, 1), (1, 0)):
                s = consts[other]
                probe = sides[side]
                if s is None:
                    continue
                if isinstance(probe, ast.Call) and isinstance(
                        probe.func, ast.Attribute) \
                        and probe.func.attr == "get" and probe.args \
                        and const_str(probe.args[0]) == "kind":
                    kinds.add(s)
                elif isinstance(probe, ast.Subscript) \
                        and const_str(probe.slice) == "kind":
                    kinds.add(s)
        elif isinstance(node, ast.Call) and terminal_name(
                node.func) == "read_ckpt" and len(node.args) >= 2:
            s = const_str(node.args[1])
            if s is not None:
                kinds.add(s)
    return sorted(kinds)


def collect_load_sites(modules: Sequence[Module],
                       index: ProjectIndex) -> List[LoadSite]:
    # which functions transitively reach a schema gate
    gated: Set[str] = set()
    callees: Dict[str, Set[str]] = {}
    for q, info in sorted(index.functions.items()):
        cs: Set[str] = set()
        hit = False
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call):
                if terminal_name(node.func) in CKPT_GATE_NAMES:
                    hit = True
                cs.update(index.resolve_call(info.module, node.func,
                                             cls=info.cls))
        callees[q] = cs
        if hit:
            gated.add(q)
    for _ in range(10):
        grew = False
        for q, cs in sorted(callees.items()):
            if q not in gated and cs & gated:
                gated.add(q)
                grew = True
        if not grew:
            break

    sites: List[LoadSite] = []
    for module in sorted(modules, key=lambda m: m.path):
        if not module.path.startswith("raft_tpu/"):
            continue
        for fn, chain in _function_chains(module):
            kinds = _load_kinds(fn)
            if not kinds or not isinstance(fn, _FUNCS):
                continue
            if "load" not in fn.name and "Load" not in fn.name:
                continue
            qname = f"{module.path}::{fn.name}"
            helper_acc: List[FieldAccess] = []
            seen: Set[str] = {qname}
            frontier = sorted(callees.get(qname, ()))
            for _depth in range(3):
                nxt: List[str] = []
                for cq in frontier:
                    if cq in seen or cq not in index.functions:
                        continue
                    seen.add(cq)
                    helper_acc += _field_accesses(index.functions[cq].node)
                    nxt += sorted(callees.get(cq, ()))
                frontier = nxt
            sites.append(LoadSite(module, fn, kinds, _field_accesses(fn),
                                  helper_acc, qname in gated))
    return sites
