"""Project-wide symbol table, call graph, and bounded interprocedural
summaries — the cross-file half of the raftlint 2.0 analysis core.

The CFG (tools/raftlint/cfg.py) answers "under which conditions does
this statement run"; this module answers "what does this call *do*".
Per top-level function and method it computes a bounded summary:

  - whether the function (transitively) **emits collectives** — lax
    collectives, ``AxisComms`` ops, ``health_barrier``, driver-level
    ``process_allgather``, and the ``mnmg_ckpt`` save/load family
    (collective by contract: every rank must enter them together);
  - whether it **returns a rank-dependent value** (taint source for the
    divergence rule: ``get_rank``/``axis_index``/``process_index``
    wrappers);
  - which class **locks it may acquire** (for the lock-order deadlock
    graph), plus how many **resources it opens** (``open``/
    ``atomic_write`` — summary completeness for future rules).

Summaries are computed by fixpoint over the project call graph with a
hard iteration bound, and call resolution is deliberately conservative:
a call resolves only through (a) a same-module top-level def, (b) an
import we can follow (``from raft_tpu.x import f`` / ``from raft_tpu
import x; x.f``), (c) ``self.m()`` within the defining class, or (d) a
project-unique name. Anything else stays unresolved — an unresolved
call contributes nothing, so the engine under-reports rather than
inventing cross-file behavior (stdlib ``ast`` only; raft_tpu is never
imported).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from tools.raftlint.engine import Module, dotted_chain, terminal_name

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

# -- what counts as a collective -----------------------------------------

#: lax-level collective primitives (distinctive names, no receiver guard)
COLLECTIVE_LAX = {"psum", "pmax", "pmin", "all_gather", "ppermute",
                  "psum_scatter", "all_to_all"}

#: AxisComms method names (comms.py) — matched as attribute calls, with a
#: receiver-root guard against stdlib/numpy collisions (functools.reduce)
COLLECTIVE_METHODS = {"allreduce", "allgather", "allgatherv", "bcast",
                      "reduce", "reducescatter", "gather", "gatherv",
                      "barrier", "shift", "device_sendrecv",
                      "device_multicast_sendrecv"}

#: host-level collective entry points (every rank must call together)
COLLECTIVE_HOST = {"health_barrier", "process_allgather"}

#: receiver roots that make a COLLECTIVE_METHODS name a false friend
_NONCOMMS_ROOTS = {"functools", "np", "numpy", "jnp", "jax", "math",
                   "operator", "itertools", "matrix", "ops", "torch"}

#: functions whose NAME marks them collective by contract even when their
#: body shows none to the AST (the mnmg_ckpt save/load family: sharded
#: checkpoint IO is a lockstep protocol — a rank skipping it deadlocks
#: or tears the checkpoint)
_SEED_COLLECTIVE_RE = re.compile(r"^(ivf_\w+_(save|load)\w*|rehydrate)$")
_SEED_COLLECTIVE_PATHS = ("raft_tpu/comms/mnmg_ckpt.py",
                          "raft_tpu/comms/resilience.py")

#: expression-level rank sources
RANK_SOURCES = {"get_rank", "axis_index", "process_index"}

#: attributes marking host health state (RankHealth and friends)
HEALTH_ATTRS = {"degraded", "coverage", "mask", "healthy_ranks",
                "live_f32", "repaired_ranks"}

#: per-host filesystem probes: ranks on different hosts can disagree
FS_PROBE_TERMS = {"exists", "isfile", "isdir", "listdir", "glob"}

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

#: reads of the MUTABLE tuned registry (core/tuned.py): ``tuned.get(..)``
#: etc. — matched as attribute calls whose receiver chain mentions a
#: tuned-ish root, or resolved into core/tuned.py readers
TUNED_READ_METHODS = {"get", "get_choice", "hints"}
_TUNED_ROOTS = {"tuned", "_tuned"}
_TUNED_MODULE = "raft_tpu/core/tuned.py"


def is_tuned_read(call: ast.Call) -> bool:
    """True when this Call syntactically reads the tuned registry
    (``tuned.get_choice(...)``, ``_tuned.hints()``)."""
    if not isinstance(call.func, ast.Attribute):
        return False
    if call.func.attr not in TUNED_READ_METHODS:
        return False
    chain = dotted_chain(call.func)
    return chain is not None and chain[0] in _TUNED_ROOTS


# -- data model -----------------------------------------------------------

@dataclasses.dataclass
class ClassInfo:
    qname: str  # "<module path>::<ClassName>"
    name: str
    module: str
    node: ast.ClassDef
    methods: Dict[str, ast.AST]
    locks: Dict[str, str]  # lock attr -> factory name (Lock/RLock/Condition)


@dataclasses.dataclass
class FnInfo:
    qname: str  # "<module path>::<fn>" or "<module path>::<Cls>.<m>"
    name: str
    module: str
    node: ast.AST
    cls: Optional[str] = None  # owning ClassInfo qname


@dataclasses.dataclass
class Summary:
    collectives: bool = False
    #: representative emitted-op tokens, deterministic order, bounded
    ops: Tuple[str, ...] = ()
    rank_source: bool = False
    acquires: FrozenSet[Tuple[str, str]] = frozenset()  # (class qname, attr)
    opens: int = 0
    #: (transitively) reads the mutable tuned registry
    #: (``tuned.get``/``get_choice``/``hints``) — the statecheck rule's
    #: "process-global but NOT process-stable" taint: a memoized trace
    #: whose build derives from a tuned read must key that read's result
    tuned_read: bool = False


def _module_of_dots(dotted: str) -> str:
    """'raft_tpu.comms.mnmg_ckpt' -> 'raft_tpu/comms/mnmg_ckpt.py'."""
    return dotted.replace(".", "/") + ".py"


class ProjectIndex:
    """Symbol table + function table + summaries over one module set."""

    def __init__(self, modules: Sequence[Module]):
        self.modules = {m.path: m for m in modules}
        self.functions: Dict[str, FnInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        #: per module: local name -> ("module", dotted) | ("symbol", dotted, name)
        self.imports: Dict[str, Dict[str, Tuple]] = {}
        #: bare name -> [fn qnames] (for unique-name resolution)
        self._by_name: Dict[str, List[str]] = {}
        #: method name -> [fn qnames across all classes]
        self._methods_by_name: Dict[str, List[str]] = {}
        for m in sorted(self.modules.values(), key=lambda x: x.path):
            self._index_module(m)
        self.summaries: Dict[str, Summary] = {}
        self._summarize()

    # -- indexing ---------------------------------------------------------
    def _index_module(self, m: Module) -> None:
        imports: Dict[str, Tuple] = {}
        pkg_parts = m.path.rsplit("/", 1)[0].split("/")
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    imports[local] = ("module",
                                      a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    # relative: resolve against this module's package
                    up = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    base = ".".join(up + ([base] if base else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    imports[local] = ("symbol", base, a.name)
        self.imports[m.path] = imports

        for node in m.tree.body:
            if isinstance(node, _FUNCS):
                q = f"{m.path}::{node.name}"
                self.functions[q] = FnInfo(q, node.name, m.path, node)
                self._by_name.setdefault(node.name, []).append(q)
            elif isinstance(node, ast.ClassDef):
                cq = f"{m.path}::{node.name}"
                methods: Dict[str, ast.AST] = {}
                locks: Dict[str, str] = {}
                for item in node.body:
                    if isinstance(item, _FUNCS):
                        methods[item.name] = item
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Assign)
                            and isinstance(sub.value, ast.Call)
                            and terminal_name(sub.value.func) in LOCK_FACTORIES):
                        for tgt in sub.targets:
                            if (isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"):
                                locks[tgt.attr] = terminal_name(sub.value.func)
                self.classes[cq] = ClassInfo(cq, node.name, m.path, node,
                                             methods, locks)
                for name, fn in methods.items():
                    q = f"{m.path}::{node.name}.{name}"
                    self.functions[q] = FnInfo(q, name, m.path, fn, cls=cq)
                    self._methods_by_name.setdefault(name, []).append(q)

    # -- call resolution --------------------------------------------------
    def resolve_call(self, module_path: str, func: ast.AST,
                     cls: Optional[str] = None) -> List[str]:
        """Conservatively resolve a call's target to project function
        qnames (empty when unknown). `cls` is the ClassInfo qname of the
        enclosing class for ``self.m()`` resolution."""
        imports = self.imports.get(module_path, {})
        if isinstance(func, ast.Name):
            local = f"{module_path}::{func.id}"
            if local in self.functions:
                return [local]
            imp = imports.get(func.id)
            if imp is not None and imp[0] == "symbol":
                target = f"{_module_of_dots(imp[1])}::{imp[2]}"
                if target in self.functions:
                    return [target]
                return []
            hits = self._by_name.get(func.id, ())
            if len(hits) == 1:
                return list(hits)
            return []
        if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            root = func.value.id
            if root == "self" and cls is not None:
                target = f"{self.classes[cls].module}::{self.classes[cls].name}.{func.attr}"
                if target in self.functions:
                    return [target]
                return []
            imp = imports.get(root)
            if imp is not None:
                dotted = imp[1] if imp[0] == "module" else f"{imp[1]}.{imp[2]}"
                target = f"{_module_of_dots(dotted)}::{func.attr}"
                if target in self.functions:
                    return [target]
                # `from raft_tpu.comms import mnmg_ckpt` comes through as
                # ("symbol", "raft_tpu.comms", "mnmg_ckpt"): the symbol IS
                # a module
                if imp[0] == "symbol":
                    target = f"{_module_of_dots(imp[1] + '.' + imp[2])}::{func.attr}"
                    if target in self.functions:
                        return [target]
            return []
        return []

    def resolve_methods_by_name(self, name: str) -> List[str]:
        """All class methods with this name (the lock-order rule's
        bounded fallback for ``obj.m()`` calls it cannot type)."""
        return sorted(self._methods_by_name.get(name, ()))

    # -- collective detection ---------------------------------------------
    def collective_token(self, call: ast.Call, module_path: str,
                         cls: Optional[str] = None) -> Optional[str]:
        """The op token when this Call emits a collective — a direct
        primitive/method name, or the name of a resolved callee whose
        summary emits. None otherwise."""
        name = terminal_name(call.func)
        if name in COLLECTIVE_LAX or name in COLLECTIVE_HOST:
            return name
        if name in COLLECTIVE_METHODS and isinstance(call.func, ast.Attribute):
            chain = dotted_chain(call.func)
            if chain is None or chain[0] not in _NONCOMMS_ROOTS:
                return name
        for q in self.resolve_call(module_path, call.func, cls=cls):
            s = self.summaries.get(q)
            if s is not None and s.collectives:
                return self.functions[q].name
        return None

    # -- summaries --------------------------------------------------------
    def _direct_facts(self, info: FnInfo):
        """(ops, rank_source, acquires, opens, callees) from the
        function's own body — nested defs included (a shard_map'd inner
        body executes when the outer function runs)."""
        ops: List[str] = []
        rank = False
        opens = 0
        callees: Set[str] = set()
        ret_callees: Set[str] = set()
        acquires: Set[Tuple[str, str]] = set()
        cls = self.classes.get(info.cls) if info.cls else None
        # the tuned READERS themselves (core/tuned.py) seed the
        # tuned_read bit so resolved calls to them propagate it
        tuned = (info.module == _TUNED_MODULE
                 and info.name in TUNED_READ_METHODS)
        for node in ast.walk(info.node):
            if isinstance(node, ast.Return) and node.value is not None:
                # rank-SOURCE means the function's *return value* is
                # rank-dependent (a get_rank wrapper) — merely using the
                # rank internally (every SPMD kernel does) must not
                # taint callers. Calls inside the returned expression
                # are kept separately so the fixpoint can propagate
                # sourceness through wrapper chains (rank_of -> my_rank
                # -> process_index).
                for n in ast.walk(node.value):
                    if isinstance(n, ast.Call):
                        if terminal_name(n.func) in RANK_SOURCES:
                            rank = True
                        ret_callees.update(self.resolve_call(
                            info.module, n.func, cls=info.cls))
            if isinstance(node, ast.Call):
                name = terminal_name(node.func)
                if name in COLLECTIVE_LAX or name in COLLECTIVE_HOST:
                    ops.append(name)
                elif (name in COLLECTIVE_METHODS
                      and isinstance(node.func, ast.Attribute)):
                    chain = dotted_chain(node.func)
                    if chain is None or chain[0] not in _NONCOMMS_ROOTS:
                        ops.append(name)
                if name in ("open", "atomic_write"):
                    opens += 1
                if is_tuned_read(node):
                    tuned = True
                callees.update(self.resolve_call(info.module, node.func,
                                                 cls=info.cls))
            elif isinstance(node, ast.withitem):
                e = node.context_expr
                if isinstance(e, ast.Call):
                    e = e.func  # with self._lock: vs with self._lock.acquire()
                if (cls is not None and isinstance(e, ast.Attribute)
                        and isinstance(e.value, ast.Name)
                        and e.value.id == "self" and e.attr in cls.locks):
                    acquires.add((cls.qname, e.attr))
        seeded = (info.module in _SEED_COLLECTIVE_PATHS
                  and _SEED_COLLECTIVE_RE.match(info.name))
        if seeded and not ops:
            ops.append(info.name)
        return (tuple(ops[:16]), rank, frozenset(acquires), opens, callees,
                ret_callees, tuned)

    def _summarize(self) -> None:
        facts = {}
        for q in sorted(self.functions):
            facts[q] = self._direct_facts(self.functions[q])
            ops, rank, acq, opens, _callees, _ret, tuned = facts[q]
            self.summaries[q] = Summary(bool(ops), ops, rank, acq, opens,
                                        tuned)
        # bounded fixpoint: propagate collectives / rank-source / lock
        # acquisitions / tuned reads through resolved calls
        # (rank-sourceness flows only through RETURN-site callees —
        # calling get_rank for internal use must not taint the caller's
        # return value)
        for _round in range(10):
            changed = False
            for q in sorted(self.functions):
                s = self.summaries[q]
                ops, rank, acq, opens, callees, ret_callees, _t = facts[q]
                new_coll = s.collectives
                new_rank = s.rank_source or any(
                    self.summaries[c].rank_source
                    for c in sorted(ret_callees) if c in self.summaries)
                new_acq = set(s.acquires)
                new_ops = list(s.ops)
                new_tuned = s.tuned_read
                for c in sorted(callees):
                    cs = self.summaries.get(c)
                    if cs is None:
                        continue
                    if cs.collectives and not new_coll:
                        new_coll = True
                        new_ops.append(self.functions[c].name)
                    if cs.tuned_read:
                        new_tuned = True
                    new_acq.update(cs.acquires)
                if len(new_acq) > 12:  # hard bound: keep summaries small
                    new_acq = set(sorted(new_acq)[:12])
                if (new_coll != s.collectives or new_rank != s.rank_source
                        or frozenset(new_acq) != s.acquires
                        or new_tuned != s.tuned_read):
                    self.summaries[q] = Summary(
                        new_coll, tuple(new_ops[:16]), new_rank,
                        frozenset(new_acq), opens, new_tuned)
                    changed = True
            if not changed:
                break


def project_index(modules: Sequence[Module]) -> ProjectIndex:
    """Build (and memoize per lint run) the ProjectIndex. Memoized on
    the first module's tree — the engine hands every project rule the
    same Module list within one run."""
    if not modules:
        return ProjectIndex(())
    anchor = modules[0].tree
    cached = getattr(anchor, "_raftlint_project", None)
    if cached is None or len(cached.modules) != len(modules):
        cached = ProjectIndex(modules)
        anchor._raftlint_project = cached
    return cached


# -- rank/health/filesystem taint ----------------------------------------

#: parameter names seeding taint (SPMD code passes rank state explicitly)
_TAINT_PARAM_SEEDS = {"rank": "rank", "ranks": "rank", "health": "health"}


#: calls whose return is "as tainted as their arguments" — pure
#: shape/value transforms the taint may flow through
_TRANSPARENT_CALLS = {"bool", "int", "float", "len", "any", "all", "sorted",
                      "min", "max", "sum", "tuple", "list", "set", "abs",
                      "range", "enumerate", "zip"}
_TRANSPARENT_ROOTS = {"np", "numpy", "jnp", "math"}


def taint_reason(expr: ast.AST, tainted: Dict[str, str],
                 index: ProjectIndex, module_path: str,
                 cls: Optional[str] = None) -> Optional[str]:
    """Why `expr` can evaluate differently across ranks, or None.
    Reasons: 'rank' (axis/process index), 'health' (liveness mask
    state), 'filesystem' (per-host fs probes).

    Calls are OPAQUE: a tainted name passed as an argument does not
    taint the call's result (``f(health)`` returns who-knows-what —
    flow-insensitive laundering through every call would taint whole
    functions within three assignments). Exceptions: the call itself is
    a source, its callee's summary returns a rank value, or it is a
    transparent value transform (``bool``/``len``/``np.*`` ...). The
    receiver chain is always inspected (``health.anything()`` stays
    tainted)."""
    found: List[str] = []

    def visit(node: ast.AST) -> None:
        if found:
            return
        if isinstance(node, ast.Call):
            name = terminal_name(node.func)
            if name in RANK_SOURCES:
                found.append("rank")
                return
            if name in FS_PROBE_TERMS:
                found.append("filesystem")
                return
            for q in index.resolve_call(module_path, node.func, cls=cls):
                s = index.summaries.get(q)
                if s is not None and s.rank_source:
                    found.append("rank")
                    return
            chain = dotted_chain(node.func)
            transparent = (
                (isinstance(node.func, ast.Name)
                 and node.func.id in _TRANSPARENT_CALLS)
                or (chain is not None and chain[0] in _TRANSPARENT_ROOTS))
            visit(node.func)
            if transparent:
                for a in node.args:
                    visit(a)
                for kw in node.keywords:
                    visit(kw.value)
            return
        if isinstance(node, ast.Attribute):
            if node.attr in HEALTH_ATTRS or node.attr == "health":
                found.append("health")
                return
        elif isinstance(node, ast.Name):
            if node.id in tainted:
                found.append(tainted[node.id])
                return
            if node.id == "health":
                found.append("health")
                return
        elif isinstance(node, (_FUNCS[0], _FUNCS[1], ast.Lambda)):
            return  # nested defs are their own analysis scope
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(expr)
    return found[0] if found else None


def local_taints(fn: ast.AST, index: ProjectIndex, module_path: str,
                 cls: Optional[str] = None) -> Dict[str, str]:
    """Local names carrying rank/health/filesystem-dependent values:
    parameter seeds plus a small forward-propagation fixpoint over the
    function's assignments (nested defs excluded — they are analyzed as
    their own functions)."""
    tainted: Dict[str, str] = {}
    if isinstance(fn, _FUNCS + (ast.Lambda,)):
        a = fn.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            if p.arg in _TAINT_PARAM_SEEDS:
                tainted[p.arg] = _TAINT_PARAM_SEEDS[p.arg]

    def own_nodes(root):
        stack = list(ast.iter_child_nodes(root))
        while stack:
            n = stack.pop()
            yield n
            if not isinstance(n, _FUNCS + (ast.Lambda,)):
                stack.extend(ast.iter_child_nodes(n))

    def target_names(t) -> Iterable[str]:
        if isinstance(t, ast.Name):
            yield t.id
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from target_names(e)

    for _round in range(4):
        changed = False
        for node in own_nodes(fn):
            value = None
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if node.value is not None:
                    value, targets = node.value, [node.target]
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                value, targets = node.iter, [node.target]
            elif isinstance(node, ast.NamedExpr):
                value, targets = node.value, [node.target]
            if value is None:
                continue
            reason = taint_reason(value, tainted, index, module_path, cls=cls)
            if reason is None:
                continue
            for name in (n for t in targets for n in target_names(t)):
                if name not in tainted:
                    tainted[name] = reason
                    changed = True
        if not changed:
            break
    return tainted
