"""kernelcheck analysis core: an abstract interpreter over
``pl.pallas_call`` sites — the raftlint 3.0 engine.

The fused kernel family (raft_tpu/ops/fused_scan.py) is 4+ kernels x 3
dtype regimes x ``chunk_valid`` variants, each with a hand-mirrored
``fits_*`` VMEM envelope and a BlockSpec/scalar-prefetch geometry that
the docstrings promise stay consistent. A drifted envelope silently
OOMs (under-charge) or refuses workloads that fit (over-charge) ON
CHIP, where a queue slot is the scarce resource; a drifted index_map
arity or operand dtype fails at Mosaic compile time — also on chip.
This module evaluates those contracts at lint time, stdlib-``ast``
only, never importing raft_tpu:

  - a **symbolic polynomial domain** (`Poly` over `Atom`s): block
    shapes, envelope formulas, and padding arithmetic evaluate to
    canonical polynomials over named symbols, so ``4 * bq * bn`` from
    the envelope and a ``(bq, bn)`` f32 block from the kernel land on
    the same monomial and byte accounting is compared term by term.
    Uninterpretable scalars (floordiv rounding, ``fused_kbuf(k)``
    calls) become structural atoms: both sides computing the same
    expression produce the same atom, and atoms evaluate concretely
    (by interpreting the called function) for probe-point checks.
  - a **module interpreter** that walks a wrapper function's body
    binding symbols at shape unpacks (``m, d = x.shape``), propagating
    operand dtypes through ``astype``/``pad``/``where``/arithmetic,
    honoring validation raises as constraints (``if q8.dtype !=
    jnp.int8 ... raise`` pins int8; ``if pw != bits * words: raise``
    rewrites ``pw``), and extracting every ``pl.pallas_call`` site:
    grid, scalar-prefetch count, BlockSpecs (shape + index_map),
    out_shape dtypes, and the operand expressions actually passed.
    Optional-operand wrappers (the PR-12 ``chunk_valid`` second
    prefetch operand) split into per-variant interpretations so the
    ``nsp``/kernel-unpack correlation is checked on both programs.
  - a **kernel-body interpreter** giving each ``ref`` its BlockSpec
    shape and operand dtype, then abstractly executing the body
    (``ref[:]``/``ref[0]`` reads, ``dot_general``, ``population_count``,
    ``fori_loop``, iota/concat/where/reductions, nested helper calls)
    to recover: MXU/VPU dot operand dtypes (the dtype-flow rule), the
    dtype each output ref finally stores (BlockSpec consistency), and
    the intermediate-buffer byte total (the envelope over-charge
    bound).

Pairing is machine-readable, the FAULT_SITES pattern: an ops module
declares ``KERNEL_ENVELOPES = {"fused_topk": ("fits_fused", {}), ...}``
(optional binding overrides pin envelope params the kernel fixes, e.g.
``{"store_itemsize": 1}`` for the int8 kernel sharing the bf16 list
envelope). Symbols unify by NAME across the kernel wrapper and its
envelope — the repo convention that both sign the same parameter names
(``k``, ``bq``, ``chunk``, ``L``, ``rot``, ``kbuf``) is what makes the
cross-check exact; an envelope parameter named ``<p>_itemsize`` binds
to operand ``<p>``'s (possibly symbolic) element size.

Deliberate approximations, documented over clever: unsupported
constructs evaluate to `UNKNOWN` and their consumers stay silent;
analysis failure of a *registered* kernel fails CLOSED (the rules
report it — a registry entry the interpreter cannot check must not
turn the gate green).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from tools.raftlint.engine import Module, dotted_chain, terminal_name

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


class CannotEval(Exception):
    """Raised when a concrete probe evaluation hits an unknown."""


# -- dtypes ---------------------------------------------------------------

ITEMSIZE = {
    "bool": 1, "int8": 1, "uint8": 1, "int16": 2, "uint16": 2,
    "float16": 2, "bfloat16": 2, "int32": 4, "uint32": 4, "float32": 4,
    "int64": 8, "uint64": 8, "float64": 8,
}

_RANK = ["bool", "int8", "uint8", "int16", "uint16", "int32", "uint32",
         "int64", "uint64", "float16", "bfloat16", "float32", "float64"]


def promote(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """Tiny dtype-promotion lattice: enough for kernel bodies (equal
    wins; float beats int beats bool; f16/bf16 mixes land on f32).
    Unknown poisons to unknown — silence, never a guess."""
    if a is None or b is None:
        return None
    if a == b:
        return a
    if {a, b} == {"float16", "bfloat16"}:
        return "float32"
    ra = _RANK.index(a) if a in _RANK else None
    rb = _RANK.index(b) if b in _RANK else None
    if ra is None or rb is None:
        return None
    return _RANK[max(ra, rb)]


def is_unsigned(dt: Optional[str]) -> bool:
    return dt is not None and dt.startswith("uint")


# -- symbolic polynomial domain -------------------------------------------


class Atom:
    """An opaque symbolic scalar polynomials treat as a variable.

    kinds: ``sym`` (a named symbol), ``itemsize`` (the element size of
    operand <name>), ``floordiv``/``mod``/``shl`` (integer ops over
    polynomial args), ``call`` (a named function application — carries
    the resolved def for concrete evaluation), ``max``/``min``,
    ``opaque`` (anything else, keyed by source dump). Identity is the
    canonical key, so two sides computing the same expression agree.
    """

    __slots__ = ("kind", "name", "args", "node", "_key")

    def __init__(self, kind: str, name: str = "", args: Tuple["Poly", ...] = (),
                 node: Optional[ast.AST] = None):
        self.kind = kind
        self.name = name
        self.args = args
        self.node = node  # FunctionDef for kind="call" (concrete eval)
        if kind == "sym":
            self._key = f"s:{name}"
        elif kind == "itemsize":
            self._key = f"i:{name}"
        else:
            self._key = f"{kind}:{name}({','.join(a.key() for a in args)})"

    def key(self) -> str:
        return self._key

    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return isinstance(other, Atom) and self._key == other._key

    def concrete(self, env: Callable[[str, str], Any],
                 resolver: Callable[[Optional[ast.AST], str, list], Any]):
        if self.kind in ("sym", "itemsize"):
            return env(self.kind, self.name)
        vals = [a.concrete(env, resolver) for a in self.args]
        if self.kind == "floordiv":
            return vals[0] // vals[1]
        if self.kind == "ceildiv":
            return -((-vals[0]) // vals[1])
        if self.kind == "mod":
            return vals[0] % vals[1]
        if self.kind == "shl":
            return int(vals[0]) << int(vals[1])
        if self.kind == "max":
            return max(vals)
        if self.kind == "min":
            return min(vals)
        if self.kind == "call":
            return resolver(self.node, self.name, vals)
        raise CannotEval(f"opaque atom {self._key!r}")


class Poly:
    """Multivariate polynomial with numeric coefficients over Atoms.
    ``terms`` maps a sorted monomial (tuple of atom keys, repetition =
    power) to its coefficient; ``atoms`` keeps key -> Atom for concrete
    evaluation. The constant polynomial has the empty monomial."""

    __slots__ = ("terms", "atoms")

    def __init__(self, terms: Dict[Tuple[str, ...], float],
                 atoms: Dict[str, Atom]):
        self.terms = {m: c for m, c in terms.items() if c != 0}
        self.atoms = atoms

    # -- constructors
    @staticmethod
    def const(c) -> "Poly":
        return Poly({(): c} if c else {}, {})

    @staticmethod
    def sym(name: str) -> "Poly":
        a = Atom("sym", name)
        return Poly({(a.key(),): 1}, {a.key(): a})

    @staticmethod
    def of_atom(a: Atom) -> "Poly":
        return Poly({(a.key(),): 1}, {a.key(): a})

    # -- queries
    def as_const(self):
        """The numeric value when constant, else None."""
        if not self.terms:
            return 0
        if len(self.terms) == 1 and () in self.terms:
            return self.terms[()]
        return None

    def key(self) -> str:
        return "+".join(f"{self.terms[m]}*{'*'.join(m)}"
                        for m in sorted(self.terms))

    def __eq__(self, other):
        return isinstance(other, Poly) and self.terms == other.terms

    def __hash__(self):
        return hash(self.key())

    # -- arithmetic
    def _merged_atoms(self, other: "Poly") -> Dict[str, Atom]:
        if not other.atoms:
            return self.atoms
        if not self.atoms:
            return other.atoms
        d = dict(self.atoms)
        d.update(other.atoms)
        return d

    def __add__(self, other: "Poly") -> "Poly":
        terms = dict(self.terms)
        for m, c in other.terms.items():
            terms[m] = terms.get(m, 0) + c
        return Poly(terms, self._merged_atoms(other))

    def __sub__(self, other: "Poly") -> "Poly":
        return self + (other * Poly.const(-1))

    def __mul__(self, other: "Poly") -> "Poly":
        terms: Dict[Tuple[str, ...], float] = {}
        for m1, c1 in self.terms.items():
            for m2, c2 in other.terms.items():
                m = tuple(sorted(m1 + m2))
                terms[m] = terms.get(m, 0) + c1 * c2
        return Poly(terms, self._merged_atoms(other))

    def _intop(self, other: "Poly", kind: str) -> "Poly":
        a, b = self.as_const(), other.as_const()
        if a is not None and b is not None and b != 0:
            if kind == "floordiv":
                return Poly.const(a // b)
            if kind == "mod":
                return Poly.const(a % b)
        if a is not None and b is not None and kind == "shl":
            return Poly.const(int(a) << int(b))
        if kind == "floordiv" and self.terms \
                and all(c < 0 for c in self.terms.values()):
            # canonicalize `-x // c` to -ceildiv(x, c): the repo's
            # ceil-pad idiom `-(-d // L) * L` then lands on a POSITIVE
            # monomial, so byte coefficients compare in the right
            # direction
            return Poly.of_atom(
                Atom("ceildiv", args=(self * Poly.const(-1), other))
            ) * Poly.const(-1)
        return Poly.of_atom(Atom(kind, args=(self, other)))

    def floordiv(self, other):
        return self._intop(other, "floordiv")

    def mod(self, other):
        return self._intop(other, "mod")

    def shl(self, other):
        return self._intop(other, "shl")

    def concrete(self, env, resolver):
        total = 0
        for m, c in self.terms.items():
            v = c
            for akey in m:
                v = v * self.atoms[akey].concrete(env, resolver)
            total += v
        return total

    def monomials_below(self, other: "Poly") -> List[Tuple[str, float, float]]:
        """Monomials where OTHER's coefficient falls short of self's —
        the under-charge witness list [(monomial repr, need, got)]."""
        out = []
        for m, c in self.terms.items():
            oc = other.terms.get(m, 0)
            if oc < c:
                out.append(("*".join(_pretty_mon(m, self.atoms)) or "1",
                            c, oc))
        return sorted(out)


def _pretty_mon(mon: Tuple[str, ...], atoms: Dict[str, Atom]) -> List[str]:
    names = []
    for k in mon:
        a = atoms.get(k)
        if a is None:
            names.append(k)
        elif a.kind in ("sym", "itemsize"):
            names.append(a.name if a.kind == "sym"
                         else f"itemsize({a.name})")
        elif a.kind == "call":
            names.append(f"{a.name}(...)")
        else:
            names.append(a.kind)
    return sorted(names)


# -- abstract values ------------------------------------------------------


class _Unknown:
    def __repr__(self):
        return "UNKNOWN"


UNKNOWN = _Unknown()


@dataclasses.dataclass
class Arr:
    """Abstract array: a (possibly unknown) symbolic shape + dtype +
    the parameter it originates from (for the itemsize convention)."""
    shape: Optional[Tuple[Poly, ...]] = None
    dtype: Optional[str] = None
    origin: Optional[str] = None

    def itemsize_poly(self) -> Poly:
        if self.dtype in ITEMSIZE:
            return Poly.const(ITEMSIZE[self.dtype])
        if self.origin:
            return Poly.of_atom(Atom("itemsize", self.origin))
        return Poly.of_atom(Atom("opaque", "itemsize?"))


@dataclasses.dataclass
class StrV:
    v: str


@dataclasses.dataclass
class BoolV:
    v: Optional[bool]  # None = unknown


class NoneV:
    def __repr__(self):
        return "None"


NONE = NoneV()


@dataclasses.dataclass
class TupleV:
    items: Tuple[Any, ...]


@dataclasses.dataclass
class DTypeV:
    name: str


@dataclasses.dataclass
class FuncV:
    node: ast.AST  # FunctionDef or Lambda
    env: Dict[str, Any]  # shared closure environment
    name: str = "<lambda>"


@dataclasses.dataclass
class BlockSpecV:
    shape: Optional[Tuple[Poly, ...]]
    index_map: Optional[FuncV]
    node: ast.AST


@dataclasses.dataclass
class SDSV:  # jax.ShapeDtypeStruct
    shape: Optional[Tuple[Poly, ...]]
    dtype: Optional[str]


@dataclasses.dataclass
class GridSpecV:  # pltpu.PrefetchScalarGridSpec
    nsp: Poly
    grid: Optional[Tuple[Poly, ...]]
    in_specs: Optional[List[Any]]
    out_specs: Optional[List[Any]]


@dataclasses.dataclass
class ModuleAlias:
    name: str  # "jnp", "lax", "pl", "pltpu", "jax", "math", "functools"


#: module aliases treated as the jax surface (matched on the imported
#: module's terminal component, so `from jax.experimental import pallas
#: as pl` and `import jax.numpy as jnp` both resolve)
_JAXY = {"numpy": "jnp", "jnp": "jnp", "lax": "lax", "pallas": "pl",
         "pl": "pl", "tpu": "pltpu", "pltpu": "pltpu", "jax": "jax",
         "math": "math", "functools": "functools"}

_DTYPE_NAMES = set(ITEMSIZE)


# -- pallas-call site record ----------------------------------------------


@dataclasses.dataclass
class DotSite:
    node: ast.Call
    lhs: Optional[str]
    rhs: Optional[str]
    preferred: Optional[str]


@dataclasses.dataclass
class PopcountSite:
    node: ast.Call
    dtype: Optional[str]


@dataclasses.dataclass
class BodyResult:
    """What interpreting one kernel variant's body produced."""
    dots: List[DotSite] = dataclasses.field(default_factory=list)
    popcounts: List[PopcountSite] = dataclasses.field(default_factory=list)
    #: ref name (kernel param, or "*<j>" for vararg-unpacked refs) ->
    #: dtype of the value last stored into it
    stores: Dict[str, Optional[str]] = dataclasses.field(default_factory=dict)
    intermediates: Poly = dataclasses.field(default_factory=lambda: Poly.const(0))
    #: positional-parameter count of the kernel def (vararg refs sit at
    #: global position n_params + j) — the store->output mapping key
    n_params: int = 0
    failed: Optional[str] = None

    def out_store_dtype(self, site: "KernelSite",
                        out_idx: int) -> Optional[str]:
        """The dtype the kernel's final store into output `out_idx`
        produced, or None when no store was observed / analyzable."""
        want = site.nsp + len(site.in_specs) + out_idx
        for name, dt in self.stores.items():
            if name.startswith("*"):
                try:
                    pos = self.n_params + int(name[1:])
                except ValueError:
                    continue
            else:
                pos = self._param_pos.get(name, -1)
            if pos == want:
                return dt
        return None

    _param_pos: Dict[str, int] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class KernelSite:
    """One ``pl.pallas_call(...)(...)`` under one wrapper variant."""
    wrapper: str
    variant: str  # e.g. "chunk_valid=None" / "chunk_valid=given"
    node: ast.Call  # the outer invocation
    call_node: ast.Call  # the pallas_call(...) call itself
    grid: Optional[Tuple[Poly, ...]]
    nsp: int
    in_specs: List[Any]
    out_specs: List[Any]
    out_shapes: List[Any]
    operands: List[Any]  # AVs aligned with in_specs (scalars stripped)
    scalar_count: Optional[int]  # starred-scalar arity if known
    kernel: Optional[FuncV]
    body: Optional[BodyResult] = None
    failed: Optional[str] = None

    def block_bytes(self) -> Tuple[Poly, Optional[str]]:
        """Per-grid-step VMEM bytes of the in/out blocks. Each block is
        charged ONCE — a buffer revisited across grid steps (an
        index_map ignoring some axes, like the flat scan's (bq, kbuf)
        outputs across the n axis) is the same VMEM allocation every
        step, so one charge is the per-step truth. Scalar-prefetch
        operands live in SMEM and are not charged."""
        total = Poly.const(0)
        for spec, op in zip(self.in_specs, self.operands):
            if not isinstance(spec, BlockSpecV) or spec.shape is None:
                return total, "in_spec block shape not analyzable"
            b = _itemsize_of(op)
            for d in spec.shape:
                b = b * d
            total = total + b
        for spec, osh in zip(self.out_specs, self.out_shapes):
            if not isinstance(spec, BlockSpecV) or spec.shape is None:
                return total, "out_spec block shape not analyzable"
            dt = osh.dtype if isinstance(osh, SDSV) else None
            if dt not in ITEMSIZE:
                return total, "out_shape dtype not analyzable"
            b = Poly.const(ITEMSIZE[dt])
            for d in spec.shape:
                b = b * d
            total = total + b
        return total, None


def _itemsize_of(op) -> Poly:
    if isinstance(op, Arr):
        return op.itemsize_poly()
    return Poly.of_atom(Atom("opaque", "itemsize?"))


# -- the module interpreter -----------------------------------------------


class ModuleInterp:
    """Interprets one module's functions abstractly. Bounded, memoless,
    defensive: anything unsupported becomes UNKNOWN."""

    MAX_DEPTH = 10

    def __init__(self, module: Module):
        self.module = module
        #: kernel-body collection context: {"dots": [], "popcounts": [],
        #: "stores": {}, "inters": {}} while a kernel body interprets,
        #: else None. A plain attribute (not env entries) so helper
        #: calls (`_extract_topk`) share the same channels.
        self.ctx: Optional[Dict[str, Any]] = None
        self.functions: Dict[str, ast.AST] = {}
        self.consts: Dict[str, Any] = {}
        self.import_terminal: Dict[str, str] = {}
        for node in module.tree.body:
            if isinstance(node, _FUNCS):
                self.functions[node.name] = node
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                v = node.value
                if isinstance(v, ast.Constant):
                    self.consts[node.targets[0].id] = v.value
                elif isinstance(v, (ast.Tuple, ast.Dict, ast.BinOp,
                                    ast.UnaryOp)):
                    self.consts[node.targets[0].id] = v  # lazy-eval node
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                self._note_import(node)

    def _note_import(self, node):
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                self.import_terminal[local] = a.name.split(".")[-1]
        else:
            for a in node.names:
                local = a.asname or a.name
                self.import_terminal[local] = a.name

    # -- environments ---------------------------------------------------
    def base_env(self) -> Dict[str, Any]:
        env: Dict[str, Any] = {}
        for local, term in self.import_terminal.items():
            if term in _JAXY:
                env[local] = ModuleAlias(_JAXY[term])
        return env

    def lookup(self, name: str, env: Dict[str, Any]):
        if name in env:
            return env[name]
        if name in self.consts:
            c = self.consts[name]
            if isinstance(c, ast.AST):
                v = self.eval(c, {})
                self.consts[name] = v if not isinstance(v, _Unknown) else c
                return v
            if isinstance(c, (int, float)):
                return Poly.const(c)
            if isinstance(c, str):
                return StrV(c)
            return UNKNOWN
        if name in self.functions:
            return FuncV(self.functions[name], {}, name)
        if name in self.import_terminal:
            term = self.import_terminal[name]
            if term in _JAXY:
                return ModuleAlias(_JAXY[term])
        return UNKNOWN

    # -- expression evaluation ------------------------------------------
    def eval(self, node: ast.AST, env: Dict[str, Any], depth: int = 0):
        try:
            out = self._eval(node, env, depth)
        except (CannotEval, RecursionError):
            return UNKNOWN
        if self.ctx is not None and isinstance(out, Arr) \
                and out.shape is not None and out.dtype in ITEMSIZE:
            b = Poly.const(ITEMSIZE[out.dtype])
            for d in out.shape:
                b = b * d
            # one charge per producing AST node: re-reads and repeated
            # helper invocations of the same op reuse the same buffer
            self.ctx["inters"].setdefault(id(node), b)
        return out

    def _eval(self, node: ast.AST, env: Dict[str, Any], depth: int):
        if depth > self.MAX_DEPTH:
            return UNKNOWN
        if isinstance(node, ast.Constant):
            v = node.value
            if isinstance(v, bool):
                return BoolV(v)
            if isinstance(v, (int, float)):
                return Poly.const(v)
            if isinstance(v, str):
                return StrV(v)
            if v is None:
                return NONE
            return UNKNOWN
        if isinstance(node, ast.Name):
            return self.lookup(node.id, env)
        if isinstance(node, ast.Tuple) or isinstance(node, ast.List):
            return TupleV(tuple(self.eval(e, env, depth + 1)
                                for e in node.elts))
        if isinstance(node, ast.Attribute):
            return self._eval_attr(node, env, depth)
        if isinstance(node, ast.BinOp):
            return self._eval_binop(node, env, depth)
        if isinstance(node, ast.UnaryOp):
            v = self.eval(node.operand, env, depth + 1)
            if isinstance(node.op, ast.USub) and isinstance(v, Poly):
                return v * Poly.const(-1)
            if isinstance(node.op, ast.Not) and isinstance(v, BoolV) \
                    and v.v is not None:
                return BoolV(not v.v)
            return UNKNOWN
        if isinstance(node, ast.Compare):
            return self._eval_compare(node, env, depth)
        if isinstance(node, ast.IfExp):
            return self._eval_ifexp(node, env, depth)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env, depth)
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(node, env, depth)
        if isinstance(node, ast.Lambda):
            return FuncV(node, env, "<lambda>")
        if isinstance(node, ast.BoolOp):
            vals = [self.eval(v, env, depth + 1) for v in node.values]
            if all(isinstance(v, BoolV) and v.v is not None for v in vals):
                bools = [v.v for v in vals]
                return BoolV(all(bools) if isinstance(node.op, ast.And)
                             else any(bools))
            return BoolV(None)
        if isinstance(node, ast.Starred):
            return self.eval(node.value, env, depth + 1)
        if isinstance(node, ast.JoinedStr):
            return UNKNOWN
        return UNKNOWN

    def _eval_attr(self, node: ast.Attribute, env, depth):
        base = self.eval(node.value, env, depth + 1)
        attr = node.attr
        if isinstance(base, ModuleAlias):
            if attr in _DTYPE_NAMES:
                return DTypeV(attr)
            if attr == "inf":
                return Poly.const(float("inf"))
            if attr == "pi":
                return Poly.const(3.141592653589793)
            if attr in ("numpy", "experimental"):
                return base
            if base.name == "jax" and attr == "lax":
                return ModuleAlias("lax")
            return ModuleAlias(f"{base.name}.{attr}")
        if attr == "shape":
            if isinstance(base, Arr) and base.shape is not None:
                return TupleV(tuple(base.shape))
            if isinstance(base, (Arr,)):
                return UNKNOWN
            return UNKNOWN
        if attr == "dtype" and isinstance(base, Arr):
            return DTypeV(base.dtype) if base.dtype else UNKNOWN
        if attr == "ndim" and isinstance(base, Arr) and base.shape is not None:
            return Poly.const(len(base.shape))
        if attr in ("T",) and isinstance(base, Arr):
            if base.shape is not None:
                return Arr(tuple(reversed(base.shape)), base.dtype,
                           base.origin)
            return Arr(None, base.dtype, base.origin)
        return UNKNOWN

    def _eval_binop(self, node: ast.BinOp, env, depth):
        lhs = self.eval(node.left, env, depth + 1)
        rhs = self.eval(node.right, env, depth + 1)
        # scalar x scalar
        if isinstance(lhs, Poly) and isinstance(rhs, Poly):
            if isinstance(node.op, ast.Add):
                return lhs + rhs
            if isinstance(node.op, ast.Sub):
                return lhs - rhs
            if isinstance(node.op, ast.Mult):
                return lhs * rhs
            if isinstance(node.op, ast.FloorDiv):
                return lhs.floordiv(rhs)
            if isinstance(node.op, ast.Mod):
                return lhs.mod(rhs)
            if isinstance(node.op, ast.LShift):
                return lhs.shl(rhs)
            if isinstance(node.op, ast.Pow):
                e = rhs.as_const()
                if e is not None and e == int(e) and 0 <= e <= 4:
                    out = Poly.const(1)
                    for _ in range(int(e)):
                        out = out * lhs
                    return out
                return UNKNOWN
            if isinstance(node.op, ast.Div):
                c = rhs.as_const()
                if c:
                    return lhs * Poly.const(1.0 / c)
                return Poly.of_atom(Atom("opaque", "div"))
            if isinstance(node.op, (ast.BitAnd, ast.BitOr, ast.BitXor)):
                return UNKNOWN
            return UNKNOWN
        # array broadcasting
        la = isinstance(lhs, Arr)
        ra = isinstance(rhs, Arr)
        if la or ra:
            if isinstance(node.op, (ast.BitAnd, ast.BitOr, ast.BitXor)):
                dt = None
                if la and ra:
                    dt = promote(lhs.dtype, rhs.dtype)
                elif la:
                    dt = lhs.dtype
                else:
                    dt = rhs.dtype
                return Arr(_broadcast(lhs if la else None,
                                      rhs if ra else None), dt)
            dt_l = lhs.dtype if la else _scalar_dtype(lhs)
            dt_r = rhs.dtype if ra else _scalar_dtype(rhs)
            if la and not ra:
                dt = lhs.dtype if _is_weak(rhs) else promote(dt_l, dt_r)
            elif ra and not la:
                dt = rhs.dtype if _is_weak(lhs) else promote(dt_l, dt_r)
            else:
                dt = promote(dt_l, dt_r)
            return Arr(_broadcast(lhs if la else None, rhs if ra else None),
                       dt)
        return UNKNOWN

    def _eval_compare(self, node: ast.Compare, env, depth):
        if len(node.ops) == 1 and isinstance(node.ops[0], (ast.Is, ast.IsNot)):
            lhs = self.eval(node.left, env, depth + 1)
            rhs = self.eval(node.comparators[0], env, depth + 1)
            if isinstance(rhs, NoneV):
                if isinstance(lhs, NoneV):
                    return BoolV(isinstance(node.ops[0], ast.Is))
                if isinstance(lhs, _Unknown):
                    return BoolV(None)
                return BoolV(isinstance(node.ops[0], ast.IsNot))
            return BoolV(None)
        vals = [self.eval(node.left, env, depth + 1)] + [
            self.eval(c, env, depth + 1) for c in node.comparators]
        if any(isinstance(v, Arr) for v in vals):
            shapes = [v for v in vals if isinstance(v, Arr)]
            sh = shapes[0]
            other = shapes[1] if len(shapes) > 1 else None
            return Arr(_broadcast(sh, other), "bool")
        if len(node.ops) == 1 and isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
            a, b = vals
            if isinstance(a, StrV) and isinstance(b, StrV):
                eq = a.v == b.v
                return BoolV(eq if isinstance(node.ops[0], ast.Eq)
                             else not eq)
            if isinstance(a, DTypeV) and isinstance(b, DTypeV):
                eq = a.name == b.name
                return BoolV(eq if isinstance(node.ops[0], ast.Eq)
                             else not eq)
            if isinstance(a, Poly) and isinstance(b, Poly):
                ca, cb = a.as_const(), b.as_const()
                if ca is not None and cb is not None:
                    eq = ca == cb
                    return BoolV(eq if isinstance(node.ops[0], ast.Eq)
                                 else not eq)
        # numeric comparisons over constants
        consts = [v.as_const() if isinstance(v, Poly) else None for v in vals]
        if all(c is not None for c in consts) and len(node.ops) >= 1:
            ok = True
            for i, op in enumerate(node.ops):
                a, b = consts[i], consts[i + 1]
                if isinstance(op, ast.Lt):
                    ok = ok and a < b
                elif isinstance(op, ast.LtE):
                    ok = ok and a <= b
                elif isinstance(op, ast.Gt):
                    ok = ok and a > b
                elif isinstance(op, ast.GtE):
                    ok = ok and a >= b
                else:
                    return BoolV(None)
            return BoolV(ok)
        return BoolV(None)

    def _eval_ifexp(self, node: ast.IfExp, env, depth):
        # the repo's `X if <name> is None else int(X)` kbuf convention:
        # analysis models the caller-supplied case, both sides alike
        test = self.eval(node.test, env, depth + 1)
        if isinstance(test, BoolV) and test.v is not None:
            return self.eval(node.body if test.v else node.orelse, env,
                             depth + 1)
        if (isinstance(node.test, ast.Compare)
                and len(node.test.ops) == 1
                and isinstance(node.test.ops[0], ast.Is)
                and isinstance(node.test.comparators[0], ast.Constant)
                and node.test.comparators[0].value is None):
            return self.eval(node.orelse, env, depth + 1)
        a = self.eval(node.body, env, depth + 1)
        b = self.eval(node.orelse, env, depth + 1)
        if isinstance(a, Poly) and isinstance(b, Poly):
            if a == b:
                return a
            ca, cb = a.as_const(), b.as_const()
            if ca is not None and cb is not None:
                # two constant arms (the `coef = 1.0 if ip else 2.0`
                # idiom): the VALUE is unknowable but scalar-ness is
                # not — an opaque atom keeps dtype flow alive without
                # guessing a number (never-guess policy)
                return Poly.of_atom(Atom("opaque", f"ifexp({ca},{cb})"))
            return UNKNOWN  # differing symbolic arms: silence
        if isinstance(a, Arr) and isinstance(b, Arr):
            dt = a.dtype if a.dtype == b.dtype else promote(a.dtype, b.dtype)
            sh = a.shape if _shapes_eq(a.shape, b.shape) else None
            return Arr(sh, dt)
        if isinstance(a, StrV) and isinstance(b, StrV) and a.v == b.v:
            return a
        return UNKNOWN

    # -- calls ----------------------------------------------------------
    def _eval_call(self, node: ast.Call, env, depth):
        fv = self.eval(node.func, env, depth + 1)
        name = terminal_name(node.func)
        args = [self.eval(a.value if isinstance(a, ast.Starred) else a,
                          env, depth + 1)
                for a in node.args]
        kwargs = {kw.arg: self.eval(kw.value, env, depth + 1)
                  for kw in node.keywords if kw.arg is not None}

        # builtins / transparent casts
        if isinstance(node.func, ast.Name):
            if name in ("int", "float", "bool", "abs", "len"):
                v = args[0] if args else UNKNOWN
                if name == "len" and isinstance(v, TupleV):
                    return Poly.const(len(v.items))
                if name in ("int", "float") and isinstance(v, Poly):
                    return v
                if name == "bool" and isinstance(v, (BoolV,)):
                    return v
                if name == "bool" and isinstance(v, Poly) \
                        and v.as_const() is not None:
                    return BoolV(bool(v.as_const()))
                return v if isinstance(v, Poly) else UNKNOWN
            if name in ("max", "min") and all(isinstance(a, Poly)
                                              for a in args):
                consts = [a.as_const() for a in args]
                if all(c is not None for c in consts):
                    return Poly.const(max(consts) if name == "max"
                                      else min(consts))
                return Poly.of_atom(Atom(name, args=tuple(args)))
            if name == "range":
                return TupleV(tuple())  # iterated symbolically
            if name == "tuple" and args and isinstance(args[0], TupleV):
                return args[0]
        # dtype constructor call: jnp.float32(x) / jnp.int32(0)
        if isinstance(fv, DTypeV):
            v = args[0] if args else UNKNOWN
            if isinstance(v, Arr):
                return Arr(v.shape, fv.name, v.origin)
            if isinstance(v, Poly):
                return v
            return UNKNOWN
        if isinstance(fv, ModuleAlias):
            return self._eval_jaxy_call(fv, name, node, args, kwargs, env,
                                        depth)
        # method calls on arrays
        if isinstance(node.func, ast.Attribute):
            base = self.eval(node.func.value, env, depth + 1)
            if isinstance(base, Arr):
                return self._eval_arr_method(base, node.func.attr, args,
                                             kwargs)
        if isinstance(fv, FuncV):
            return self.call_function(fv, node, args, kwargs, depth)
        # unresolved call on scalars: a structural atom, so both the
        # wrapper and the envelope calling e.g. lane_padded(x) agree
        if name and all(isinstance(a, Poly) for a in args) and args \
                and not kwargs:
            fn_node = self.functions.get(name)
            return Poly.of_atom(Atom("call", name, tuple(args), fn_node))
        return UNKNOWN

    def call_function(self, fv: FuncV, node: Optional[ast.Call],
                      args: list, kwargs: dict, depth: int):
        fn = fv.node
        if isinstance(fn, ast.Lambda):
            local = dict(fv.env)
            params = fn.args.args
            for p, a in zip(params, args):
                local[p.arg] = a
            if fn.args.vararg is not None:
                local[fn.args.vararg.arg] = TupleV(tuple(args[len(params):]))
            return self.eval(fn.body, local, depth + 1)
        local = dict(fv.env)
        self.bind_params(fn, local, args, kwargs)
        exec_ = _BodyExec(self, local, depth + 1)
        exec_.run(fn.body)
        if exec_.retval is not None and not isinstance(exec_.retval, _Unknown):
            return exec_.retval
        # uninterpretable scalar-only project call -> structural atom:
        # both sides of a kernel/envelope pair computing `helper(k)`
        # still land on the same monomial
        if args and all(isinstance(a, Poly) for a in args) and not kwargs \
                and fn.name in self.functions:
            return Poly.of_atom(Atom("call", fn.name, tuple(args), fn))
        return UNKNOWN

    def bind_params(self, fn, local, args, kwargs):
        a = fn.args
        params = a.posonlyargs + a.args
        for i, p in enumerate(params):
            if i < len(args):
                local[p.arg] = args[i]
            elif p.arg in kwargs:
                local[p.arg] = kwargs[p.arg]
        if a.vararg is not None:
            local[a.vararg.arg] = TupleV(tuple(args[len(params):]))
        for p in a.kwonlyargs:
            if p.arg in kwargs:
                local[p.arg] = kwargs[p.arg]
        # defaults for anything unbound
        defaults = list(zip(reversed(params), reversed(a.defaults)))
        for p, d in defaults:
            if p.arg not in local:
                local[p.arg] = self.eval(d, {})
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if p.arg not in local and d is not None:
                local[p.arg] = self.eval(d, {})

    def _eval_arr_method(self, base: Arr, attr: str, args, kwargs):
        if attr == "astype":
            dt = args[0].name if args and isinstance(args[0], DTypeV) else None
            return Arr(base.shape, dt, base.origin)
        if attr == "reshape":
            return Arr(None, base.dtype, base.origin)
        if attr == "sum":
            return Arr(None, base.dtype, base.origin)
        return UNKNOWN

    def _eval_jaxy_call(self, mod: ModuleAlias, name: str, node: ast.Call,
                        args, kwargs, env, depth):
        m = mod.name.split(".")[0]
        if m in ("jnp", "lax", "jax"):
            if name in ("asarray", "array"):
                v = args[0] if args else UNKNOWN
                dt = None
                if len(args) > 1 and isinstance(args[1], DTypeV):
                    dt = args[1].name
                elif isinstance(kwargs.get("dtype"), DTypeV):
                    dt = kwargs["dtype"].name
                if isinstance(v, Arr):
                    return Arr(v.shape, dt or v.dtype, v.origin)
                return Arr(None, dt, _origin_of(v))
            if name in ("zeros", "ones", "empty"):
                sh = _as_shape(args[0]) if args else None
                dt = "float32"
                if len(args) > 1 and isinstance(args[1], DTypeV):
                    dt = args[1].name
                elif isinstance(kwargs.get("dtype"), DTypeV):
                    dt = kwargs["dtype"].name
                return Arr(sh, dt)
            if name == "full":
                sh = _as_shape(args[0]) if args else None
                dt = None
                if len(args) > 2 and isinstance(args[2], DTypeV):
                    dt = args[2].name
                elif isinstance(kwargs.get("dtype"), DTypeV):
                    dt = kwargs["dtype"].name
                elif len(args) > 1:
                    dt = _value_dtype(args[1])
                return Arr(sh, dt)
            if name == "pad":
                v = args[0] if args else UNKNOWN
                if isinstance(v, Arr):
                    return Arr(None, v.dtype, v.origin)
                return UNKNOWN
            if name == "where":
                a = args[1] if len(args) > 1 else UNKNOWN
                b = args[2] if len(args) > 2 else UNKNOWN
                aa = a if isinstance(a, Arr) else None
                bb = b if isinstance(b, Arr) else None
                cond = args[0] if isinstance(args[0], Arr) else None
                sh = _broadcast(aa or cond, bb)
                if aa and bb:
                    if _is_weak_arrpair(aa, bb):
                        dt = aa.dtype or bb.dtype
                    else:
                        dt = promote(aa.dtype, bb.dtype)
                elif aa:
                    dt = aa.dtype
                elif bb:
                    dt = bb.dtype
                else:
                    dt = None
                org = (aa.origin if aa else None) or (bb.origin if bb else None)
                return Arr(sh, dt, org)
            if name in ("sum", "min", "max", "mean", "prod", "any", "all"):
                v = args[0] if args else UNKNOWN
                if not isinstance(v, Arr):
                    return UNKNOWN
                dt = ("bool" if name in ("any", "all") else v.dtype)
                return _reduce(v, kwargs, args, dt)
            if name in ("maximum", "minimum"):
                a = args[0] if args else UNKNOWN
                b = args[1] if len(args) > 1 else UNKNOWN
                aa = a if isinstance(a, Arr) else None
                bb = b if isinstance(b, Arr) else None
                if aa and bb:
                    dt = promote(aa.dtype, bb.dtype)
                elif aa:
                    dt = aa.dtype
                elif bb:
                    dt = bb.dtype
                else:
                    dt = None
                return Arr(_broadcast(aa, bb), dt)
            if name == "concatenate":
                return _concat(args, kwargs)
            if name == "stack":
                parts = args[0].items if args and isinstance(args[0], TupleV) \
                    else ()
                arrs = [p for p in parts if isinstance(p, Arr)]
                if not arrs:
                    return UNKNOWN
                dt = arrs[0].dtype
                for a2 in arrs[1:]:
                    dt = promote(dt, a2.dtype)
                return Arr(None, dt)
            if name in ("sqrt", "exp", "log", "abs", "square", "negative"):
                v = args[0] if args else UNKNOWN
                if isinstance(v, Arr):
                    return Arr(v.shape, v.dtype, v.origin)
                if isinstance(v, Poly):
                    return Poly.of_atom(Atom("opaque", name))
                return UNKNOWN
            if name == "broadcasted_iota":
                dt = args[0].name if args and isinstance(args[0], DTypeV) \
                    else None
                sh = _as_shape(args[1]) if len(args) > 1 else None
                return Arr(sh, dt)
            if name == "population_count":
                v = args[0] if args else UNKNOWN
                if self.ctx is not None:
                    self.ctx["popcounts"].append(
                        PopcountSite(node, v.dtype if isinstance(v, Arr)
                                     else None))
                if isinstance(v, Arr):
                    return Arr(v.shape, v.dtype, v.origin)
                return UNKNOWN
            if name in ("dot_general", "dot"):
                return self._eval_dot(node, args, kwargs, env)
            if name == "fori_loop":
                fn = args[2] if len(args) > 2 else UNKNOWN
                init = args[3] if len(args) > 3 else UNKNOWN
                if isinstance(fn, FuncV):
                    return self.call_function(
                        fn, None, [Poly.sym("__loop_i"), init], {}, depth)
                return init
            if name == "top_k":
                v = args[0] if args else UNKNOWN
                if isinstance(v, Arr):
                    return TupleV((Arr(None, v.dtype),
                                   Arr(None, "int32")))
                return UNKNOWN
            if name == "take_along_axis":
                v = args[0] if args else UNKNOWN
                if isinstance(v, Arr):
                    return Arr(None, v.dtype)
                return UNKNOWN
            if name == "ShapeDtypeStruct":
                sh = _as_shape(args[0]) if args else _as_shape(
                    kwargs.get("shape"))
                dtv = (args[1] if len(args) > 1 else kwargs.get("dtype"))
                dt = dtv.name if isinstance(dtv, DTypeV) else None
                return SDSV(sh, dt)
            return UNKNOWN
        if m == "pl":
            if name == "BlockSpec":
                sh = _as_shape(args[0]) if args else _as_shape(
                    kwargs.get("block_shape"))
                imap = None
                cand = args[1] if len(args) > 1 else kwargs.get("index_map")
                if isinstance(cand, FuncV):
                    imap = cand
                return BlockSpecV(sh, imap, node)
            if name == "program_id":
                return Poly.sym("__pid")
            if name == "when":
                return UNKNOWN  # handled as a decorator in _BodyExec
            if name == "pallas_call":
                return UNKNOWN  # handled at the invocation site
            return UNKNOWN
        if m == "pltpu":
            if name == "PrefetchScalarGridSpec":
                nsp = kwargs.get("num_scalar_prefetch",
                                 args[0] if args else Poly.const(0))
                grid = _as_shape(kwargs.get("grid"))
                ins = kwargs.get("in_specs")
                outs = kwargs.get("out_specs")
                return GridSpecV(
                    nsp if isinstance(nsp, Poly) else Poly.const(0),
                    grid,
                    list(ins.items) if isinstance(ins, TupleV) else None,
                    list(outs.items) if isinstance(outs, TupleV) else None)
            return UNKNOWN
        if m == "math":
            if name == "sqrt" and args and isinstance(args[0], Poly):
                return Poly.of_atom(Atom("opaque", "sqrt"))
            return UNKNOWN
        return UNKNOWN

    def _eval_dot(self, node: ast.Call, args, kwargs, env):
        a = args[0] if args else UNKNOWN
        b = args[1] if len(args) > 1 else UNKNOWN
        pref = kwargs.get("preferred_element_type")
        pref_name = pref.name if isinstance(pref, DTypeV) else None
        site = DotSite(node,
                       a.dtype if isinstance(a, Arr) else None,
                       b.dtype if isinstance(b, Arr) else None,
                       pref_name)
        if self.ctx is not None:
            self.ctx["dots"].append(site)
        sh = None
        dn = kwargs.get("dimension_numbers")
        if isinstance(a, Arr) and isinstance(b, Arr) \
                and a.shape is not None and b.shape is not None \
                and isinstance(dn, TupleV) and len(dn.items) == 2:
            contract = dn.items[0]
            batch = dn.items[1]
            if isinstance(contract, TupleV) and isinstance(batch, TupleV) \
                    and _all_empty(batch):
                lc = _int_tuple(contract.items[0])
                rc = _int_tuple(contract.items[1])
                if lc is not None and rc is not None:
                    sh = tuple(d for i, d in enumerate(a.shape)
                               if i not in lc) + \
                         tuple(d for i, d in enumerate(b.shape)
                               if i not in rc)
        dt = pref_name or promote(site.lhs, site.rhs)
        return Arr(sh, dt)


def _all_empty(batch: TupleV) -> bool:
    return all(isinstance(x, TupleV) and not x.items for x in batch.items)


def _int_tuple(v) -> Optional[Tuple[int, ...]]:
    if not isinstance(v, TupleV):
        return None
    out = []
    for x in v.items:
        if isinstance(x, Poly) and x.as_const() is not None:
            out.append(int(x.as_const()))
        else:
            return None
    return tuple(out)


def _as_shape(v) -> Optional[Tuple[Poly, ...]]:
    if isinstance(v, TupleV) and all(isinstance(x, Poly) for x in v.items):
        return tuple(v.items)
    return None


def _origin_of(v) -> Optional[str]:
    return v.origin if isinstance(v, Arr) else None


def _scalar_dtype(v) -> Optional[str]:
    if isinstance(v, Poly):
        c = v.as_const()
        if c is not None and isinstance(c, float) and not float(c).is_integer():
            return "float32"
        return None  # weak int scalar
    return None


def _is_weak(v) -> bool:
    return isinstance(v, Poly)


def _is_weak_arrpair(a: Arr, b: Arr) -> bool:
    return a.dtype is None or b.dtype is None or a.dtype == b.dtype


def _shapes_eq(a, b) -> bool:
    if a is None or b is None or len(a) != len(b):
        return False
    return all(x == y for x, y in zip(a, b))


def _broadcast(a: Optional[Arr], b: Optional[Arr]):
    sa = a.shape if a is not None else None
    sb = b.shape if b is not None else None
    if sa is None and sb is None:
        return None
    if sa is None:
        return sb
    if sb is None:
        return sa
    # right-align; prefer the non-1 dim (1s broadcast away)
    la, lb = len(sa), len(sb)
    n = max(la, lb)
    out = []
    one = Poly.const(1)
    for i in range(n):
        da = sa[la - n + i] if la - n + i >= 0 else one
        db = sb[lb - n + i] if lb - n + i >= 0 else one
        if da == one:
            out.append(db)
        elif db == one or da == db:
            out.append(da)
        else:
            out.append(da)  # symbolic mismatch: keep left (bounded guess)
    return tuple(out)


def _reduce(v: Arr, kwargs, args, dt) -> Arr:
    if v.shape is None:
        return Arr(None, dt, v.origin)
    axis = kwargs.get("axis", args[1] if len(args) > 1 else None)
    keep = kwargs.get("keepdims")
    keepdims = isinstance(keep, BoolV) and keep.v is True
    if isinstance(axis, Poly) and axis.as_const() is not None:
        ax = int(axis.as_const()) % len(v.shape)
        if keepdims:
            sh = tuple(Poly.const(1) if i == ax else d
                       for i, d in enumerate(v.shape))
        else:
            sh = tuple(d for i, d in enumerate(v.shape) if i != ax)
        return Arr(sh, dt, v.origin)
    if axis is None or isinstance(axis, NoneV):
        return Arr((), dt, v.origin)
    return Arr(None, dt, v.origin)


def _concat(args, kwargs):
    parts = args[0].items if args and isinstance(args[0], TupleV) else None
    if parts is None:
        return UNKNOWN
    arrs = [p for p in parts if isinstance(p, Arr)]
    if len(arrs) != len(parts) or not arrs:
        return UNKNOWN
    dt = arrs[0].dtype
    for a in arrs[1:]:
        dt = promote(dt, a.dtype)
    axis = kwargs.get("axis", args[1] if len(args) > 1 else Poly.const(0))
    if any(a.shape is None for a in arrs) or not isinstance(axis, Poly) \
            or axis.as_const() is None:
        return Arr(None, dt)
    ax = int(axis.as_const()) % len(arrs[0].shape)
    if any(len(a.shape) != len(arrs[0].shape) for a in arrs):
        return Arr(None, dt)
    sh = []
    for i in range(len(arrs[0].shape)):
        if i == ax:
            total = Poly.const(0)
            for a in arrs:
                total = total + a.shape[i]
            sh.append(total)
        else:
            sh.append(arrs[0].shape[i])
    return Arr(tuple(sh), dt)


# -- statement execution (wrapper + kernel bodies) ------------------------


class _Return(Exception):
    pass


class _BodyExec:
    """Executes a function body's statements over the abstract env.
    Used for wrapper functions, kernel bodies, and helper calls alike;
    collection side channels (__dots__ etc.) live in the env."""

    def __init__(self, interp: ModuleInterp, env: Dict[str, Any],
                 depth: int):
        self.interp = interp
        self.env = env
        self.depth = depth
        self.retval = None

    def run(self, stmts: Sequence[ast.stmt]):
        try:
            self._run(stmts)
        except _Return:
            pass
        except (CannotEval, RecursionError):
            pass

    def _run(self, stmts):
        for s in stmts:
            self._stmt(s)

    def eval(self, node):
        return self.interp.eval(node, self.env, self.depth)

    def _assign_target(self, tgt, val):
        if isinstance(tgt, ast.Name):
            self.env[tgt.id] = val
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            items = None
            if isinstance(val, TupleV):
                items = val.items
            if items is not None and len(items) == len(tgt.elts):
                for t, v in zip(tgt.elts, items):
                    self._assign_target(t, v)
            else:
                for t in tgt.elts:
                    self._assign_target(t, UNKNOWN)
        elif isinstance(tgt, ast.Subscript):
            base = self.interp.eval(tgt.value, self.env, self.depth)
            if isinstance(base, Arr) and base.origin \
                    and base.origin.startswith("ref:") \
                    and self.interp.ctx is not None:
                dt = val.dtype if isinstance(val, Arr) else _value_dtype(val)
                self.interp.ctx["stores"][base.origin[4:]] = dt
        # attribute targets: ignored

    def _shape_unpack(self, node: ast.Assign) -> bool:
        """``m, d = x.shape`` / ``n = y.shape[0]`` bind fresh symbols by
        TARGET name — the convention that makes wrapper and envelope
        polynomials comparable."""
        v = node.value
        tgt = node.targets[0] if len(node.targets) == 1 else None
        if tgt is None:
            return False
        if isinstance(v, ast.Attribute) and v.attr == "shape" \
                and isinstance(tgt, (ast.Tuple, ast.List)):
            base = self.interp.eval(v.value, self.env, self.depth)
            if isinstance(base, Arr):
                if base.shape is not None and len(base.shape) == len(tgt.elts):
                    for t, d in zip(tgt.elts, base.shape):
                        self._assign_target(t, d)
                    return True
                dims = []
                for t in tgt.elts:
                    if isinstance(t, ast.Name):
                        p = Poly.sym(t.id)
                    else:
                        p = Poly.sym("_")
                    dims.append(p)
                    self._assign_target(t, p)
                base.shape = tuple(dims)
                return True
        if isinstance(v, ast.Subscript) and isinstance(v.value, ast.Attribute) \
                and v.value.attr == "shape" and isinstance(tgt, ast.Name):
            base = self.interp.eval(v.value.value, self.env, self.depth)
            idx = self.interp.eval(v.slice, self.env, self.depth)
            if isinstance(base, Arr) and isinstance(idx, Poly) \
                    and idx.as_const() is not None:
                i = int(idx.as_const())
                if base.shape is not None and 0 <= i < len(base.shape):
                    self._assign_target(tgt, base.shape[i])
                else:
                    self._assign_target(tgt, Poly.sym(tgt.id))
                return True
        return False

    def _constraints_from_raise_guard(self, node: ast.If) -> bool:
        """``if <cond>: raise`` — on the fallthrough path the condition
        is False. Exploits two shapes: dtype pins (``x.dtype !=
        jnp.int8``) and symbol rewrites (``pw != int(bits) * W``)."""
        if not (node.body and all(isinstance(s, ast.Raise)
                                  for s in node.body) and not node.orelse):
            return False
        conds = []
        t = node.test
        if isinstance(t, ast.BoolOp) and isinstance(t.op, ast.Or):
            conds = list(t.values)
        else:
            conds = [t]
        for c in conds:
            if isinstance(c, ast.Compare) and len(c.ops) == 1 \
                    and isinstance(c.ops[0], ast.NotEq):
                lhs, rhs = c.left, c.comparators[0]
                # dtype pin
                if isinstance(lhs, ast.Attribute) and lhs.attr == "dtype":
                    base = self.interp.eval(lhs.value, self.env, self.depth)
                    dtv = self.interp.eval(rhs, self.env, self.depth)
                    if isinstance(base, Arr) and isinstance(dtv, DTypeV):
                        base.dtype = dtv.name
                    continue
                # symbol rewrite: lhs is a plain bound symbol
                if isinstance(lhs, ast.Name):
                    cur = self.env.get(lhs.id)
                    new = self.interp.eval(rhs, self.env, self.depth)
                    if isinstance(cur, Poly) and isinstance(new, Poly) \
                            and cur.key() == Poly.sym(lhs.id).key():
                        self.env[lhs.id] = new
        return True

    def _stmt(self, node: ast.stmt):
        interp = self.interp
        if isinstance(node, ast.Assign):
            if self._shape_unpack(node):
                return
            val = self.eval(node.value)
            for t in node.targets:
                self._assign_target(t, val)
            return
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if node.value is not None:
                if isinstance(node, ast.AugAssign):
                    synth = ast.BinOp(left=_load_of(node.target),
                                      op=node.op, right=node.value)
                    ast.copy_location(synth, node)
                    ast.fix_missing_locations(synth)
                    val = self.eval(synth)
                else:
                    val = self.eval(node.value)
                self._assign_target(node.target, val)
            return
        if isinstance(node, ast.If):
            if self._constraints_from_raise_guard(node):
                return
            test = interp.eval(node.test, self.env, self.depth)
            if isinstance(test, BoolV) and test.v is not None:
                self._run(node.body if test.v else node.orelse)
                return
            # unknown test: execute both arms (later wins — the wrapper
            # code under analysis is straight-line dispatch)
            self._run(node.body)
            self._run(node.orelse)
            return
        if isinstance(node, ast.Return):
            if node.value is not None:
                self.retval = self.eval(node.value)
            raise _Return()
        if isinstance(node, ast.Expr):
            self.eval(node.value)
            return
        if isinstance(node, _FUNCS):
            # `@pl.when(cond) def _():` executes its body in place (a
            # predicated region, not a definition); a plain nested def
            # binds a FuncV for later helper calls
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) \
                        and terminal_name(dec.func) == "when":
                    self.eval(dec.args[0]) if dec.args else None
                    self._run(node.body)
                    return
            self.env[node.name] = FuncV(node, self.env, node.name)
            return
        if isinstance(node, ast.For):
            # one symbolic iteration: loop buffers are reused, so one
            # pass is the per-step accounting
            it = node.iter
            bound_names = []
            if isinstance(node.target, ast.Name):
                bound_names = [node.target.id]
            for nm in bound_names:
                self.env[nm] = Poly.sym(f"__{nm}")
            if isinstance(it, ast.Call) and terminal_name(it.func) == "range":
                pass
            self._run(node.body)
            return
        if isinstance(node, ast.While):
            self._run(node.body)
            return
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            interp._note_import(node)
            for local, term in list(interp.import_terminal.items()):
                if term in _JAXY and local not in self.env:
                    self.env[local] = ModuleAlias(_JAXY[term])
            return
        if isinstance(node, ast.With):
            self._run(node.body)
            return
        if isinstance(node, ast.Try):
            self._run(node.body)
            return
        if isinstance(node, (ast.Raise, ast.Pass, ast.Delete, ast.Assert,
                             ast.Break, ast.Continue, ast.Global,
                             ast.Nonlocal, ast.ClassDef)):
            return
        return


def _load_of(target):
    new = ast.Name(id=target.id, ctx=ast.Load()) \
        if isinstance(target, ast.Name) else target
    return new


def _value_dtype(v) -> Optional[str]:
    if isinstance(v, Arr):
        return v.dtype
    if isinstance(v, Poly):
        return _scalar_dtype(v)
    return None


# -- pallas_call site extraction ------------------------------------------


def _split_params(fn: ast.AST) -> List[str]:
    """Optional=None parameters the wrapper branches on with ``is [not]
    None`` statements — each doubles the variant set (the chunk_valid /
    valid optional-operand pattern). Capped at 2."""
    a = fn.args
    params = a.posonlyargs + a.args + a.kwonlyargs
    defaults = {}
    pos = a.posonlyargs + a.args
    for p, d in zip(reversed(pos), reversed(a.defaults)):
        defaults[p.arg] = d
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None:
            defaults[p.arg] = d
    none_params = {p.arg for p in params
                   if isinstance(defaults.get(p.arg), ast.Constant)
                   and defaults[p.arg].value is None}
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.Is, ast.IsNot)) \
                and isinstance(node.left, ast.Name) \
                and node.left.id in none_params \
                and isinstance(node.comparators[0], ast.Constant) \
                and node.comparators[0].value is None:
            # the `X if p is None else int(p)` width idiom is
            # canonicalized to the provided branch, not split
            if node.left.id not in out and not _is_width_idiom(fn, node):
                out.append(node.left.id)
    return out[:2]


def _is_width_idiom(fn, cmp_node) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.IfExp) and node.test is cmp_node:
            return True
    return False


def extract_sites(interp: ModuleInterp, fn: ast.AST) -> List[KernelSite]:
    """Interpret wrapper `fn` (per optional-operand variant) and return
    every pallas_call invocation found, fully evaluated."""
    sites: List[KernelSite] = []
    splits = _split_params(fn)
    variants: List[Dict[str, Any]] = [{}]
    for p in splits:
        variants = [dict(v, **{p: given}) for v in variants
                    for given in (False, True)]
    for assign in variants:
        label = ",".join(f"{k}={'given' if v else 'None'}"
                         for k, v in sorted(assign.items())) or "default"
        env = interp.base_env()
        a = fn.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            if p.arg in assign:
                env[p.arg] = (Arr(None, None, p.arg) if assign[p.arg]
                              else NONE)
            else:
                env[p.arg] = Arr(None, None, p.arg)
        # scalar-looking params: rebind on first arithmetic use is
        # implicit — shape-unpack targets create the real symbols; the
        # k/bits/bq/bn style params bind as symbols directly
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            if p.arg in assign:
                continue
            if _used_as_scalar(fn, p.arg):
                env[p.arg] = Poly.sym(p.arg)
        collector: List[KernelSite] = []
        env["__pallas_sites__"] = collector
        exec_ = _PallasExec(interp, env, 0)
        exec_.wrapper_name = getattr(fn, "name", "<fn>")
        exec_.variant = label
        exec_.run(fn.body)
        sites.extend(collector)
    return sites


def _used_as_scalar(fn, name) -> bool:
    """A parameter consumed by arithmetic/comparison/int() — bind it as
    a symbol, not an abstract array."""
    class V(ast.NodeVisitor):
        found = False

        def visit_BinOp(self, n):
            for side in (n.left, n.right):
                if isinstance(side, ast.Name) and side.id == name:
                    self.found = True
            self.generic_visit(n)

        def visit_Compare(self, n):
            for side in [n.left] + n.comparators:
                if isinstance(side, ast.Name) and side.id == name:
                    self.found = True
            self.generic_visit(n)

        def visit_Call(self, n):
            if terminal_name(n.func) in ("int", "float", "bool", "max",
                                         "min", "range", "fused_kbuf"):
                for a2 in n.args:
                    if isinstance(a2, ast.Name) and a2.id == name:
                        self.found = True
            self.generic_visit(n)

        def visit_UnaryOp(self, n):
            if isinstance(n.operand, ast.Name) and n.operand.id == name:
                self.found = True
            self.generic_visit(n)

    v = V()
    v.visit(fn)
    return v.found


class _PallasExec(_BodyExec):
    """A _BodyExec that recognizes ``pl.pallas_call(...)(operands)``."""

    wrapper_name = "<fn>"
    variant = "default"

    def eval(self, node):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Call) \
                and terminal_name(node.func.func) == "pallas_call":
            site = self._extract(node)
            self.env["__pallas_sites__"].append(site)
            # result: tuple of Arrs per out_shape
            outs = []
            for osh in site.out_shapes:
                if isinstance(osh, SDSV):
                    outs.append(Arr(osh.shape, osh.dtype))
                else:
                    outs.append(UNKNOWN)
            return TupleV(tuple(outs)) if len(outs) != 1 else outs[0]
        return super().eval(node)

    def _extract(self, node: ast.Call) -> KernelSite:
        interp = self.interp
        inner = node.func
        kwargs = {kw.arg: interp.eval(kw.value, self.env, self.depth)
                  for kw in inner.keywords if kw.arg is not None}
        kernel_v = interp.eval(inner.args[0], self.env, self.depth) \
            if inner.args else UNKNOWN
        if not isinstance(kernel_v, FuncV):
            kernel_v = None
        grid = _as_shape(kwargs.get("grid"))
        nsp_poly = Poly.const(0)
        in_specs = kwargs.get("in_specs")
        out_specs = kwargs.get("out_specs")
        gs = kwargs.get("grid_spec")
        if isinstance(gs, GridSpecV):
            grid = gs.grid if grid is None else grid
            nsp_poly = gs.nsp
            if gs.in_specs is not None:
                in_specs = TupleV(tuple(gs.in_specs))
            if gs.out_specs is not None:
                out_specs = TupleV(tuple(gs.out_specs))
        ins = list(in_specs.items) if isinstance(in_specs, TupleV) else []
        if isinstance(out_specs, BlockSpecV):
            outs = [out_specs]
        else:
            outs = list(out_specs.items) if isinstance(out_specs, TupleV) \
                else []
        osh = kwargs.get("out_shape")
        if isinstance(osh, SDSV):
            oshapes: List[Any] = [osh]
        else:
            oshapes = list(osh.items) if isinstance(osh, TupleV) else []
        nsp_c = nsp_poly.as_const()
        nsp = int(nsp_c) if nsp_c is not None else 0

        # operands of the invocation
        operands: List[Any] = []
        scalar_count: Optional[int] = 0
        for arg in node.args:
            if isinstance(arg, ast.Starred):
                sv = interp.eval(arg.value, self.env, self.depth)
                if isinstance(sv, TupleV):
                    scalar_count = (scalar_count or 0) + len(sv.items)
                else:
                    scalar_count = None
            else:
                operands.append(interp.eval(arg, self.env, self.depth))
        if scalar_count == 0 and nsp and len(operands) >= nsp:
            # scalars passed positionally, not starred
            scalar_count = nsp
            operands = operands[nsp:]

        site = KernelSite(
            wrapper=self.wrapper_name, variant=self.variant, node=node,
            call_node=inner, grid=grid, nsp=nsp, in_specs=ins,
            out_specs=outs, out_shapes=oshapes, operands=operands,
            scalar_count=scalar_count, kernel=kernel_v,
        )
        site.body = interpret_kernel_body(interp, site)
        return site


# -- kernel body interpretation -------------------------------------------


def interpret_kernel_body(interp: ModuleInterp,
                          site: KernelSite) -> BodyResult:
    res = BodyResult()
    kf = site.kernel
    if kf is None:
        res.failed = "kernel function not resolvable"
        return res
    fn = kf.node
    if not isinstance(fn, _FUNCS):
        res.failed = "kernel is not a def"
        return res
    # ref abstract values: scalars, then ins, then outs
    refs: List[Any] = []
    for i in range(site.nsp):
        refs.append(Arr(None, "int32", f"ref:__scalar{i}"))
    for spec, op in zip(site.in_specs, site.operands):
        sh = spec.shape if isinstance(spec, BlockSpecV) else None
        dt = op.dtype if isinstance(op, Arr) else None
        org = (op.origin if isinstance(op, Arr) else None)
        refs.append(Arr(sh, dt, f"ref:{org or '?'}"))
    for j, (spec, osh) in enumerate(zip(site.out_specs, site.out_shapes)):
        sh = spec.shape if isinstance(spec, BlockSpecV) else None
        dt = osh.dtype if isinstance(osh, SDSV) else None
        refs.append(Arr(sh, dt, f"ref:__out{j}"))

    env = dict(kf.env)
    for k, v in interp.base_env().items():
        env.setdefault(k, v)
    a = fn.args
    params = a.posonlyargs + a.args
    res.n_params = len(params)
    res._param_pos = {p.arg: i for i, p in enumerate(params)}
    for i, p in enumerate(params):
        env[p.arg] = refs[i] if i < len(refs) else UNKNOWN
        if i < len(refs) and isinstance(refs[i], Arr):
            # stores are recorded against the param NAME for the
            # blockspec-consistency check
            refs[i].origin = f"ref:{p.arg}"
    if a.vararg is not None:
        rest = refs[len(params):]
        for j, r in enumerate(rest):
            if isinstance(r, Arr):
                r.origin = f"ref:*{j}"
        env[a.vararg.arg] = TupleV(tuple(rest))
    ctx = {"dots": [], "popcounts": [], "stores": {}, "inters": {}}
    prev = interp.ctx
    interp.ctx = ctx
    try:
        exec_ = _BodyExec(interp, env, 1)
        exec_.run(fn.body)
    finally:
        interp.ctx = prev
    res.dots = ctx["dots"]
    res.popcounts = ctx["popcounts"]
    res.stores = ctx["stores"]
    total = Poly.const(0)
    for p in ctx["inters"].values():
        total = total + p  # one charge per producing AST node
    res.intermediates = total
    return res


# -- subscript handling on abstract arrays --------------------------------


def _index_arr(base: Arr, idx) -> Any:
    """ref[:], ref[0], ref[i], arr[:, j][:, None], shape-tuple slices."""
    if base.shape is None:
        return Arr(None, base.dtype, base.origin)
    items = idx if isinstance(idx, tuple) else (idx,)
    shape = list(base.shape)
    out: List[Poly] = []
    pos = 0
    for it in items:
        if it is Ellipsis:
            return Arr(None, base.dtype, base.origin)
        if isinstance(it, NoneV):
            out.append(Poly.const(1))
            continue
        if pos >= len(shape):
            return Arr(None, base.dtype, base.origin)
        if isinstance(it, slice):
            out.append(shape[pos])
            pos += 1
        elif isinstance(it, Poly):
            pos += 1  # integer index: axis dropped
        else:
            pos += 1
    out.extend(shape[pos:])
    return Arr(tuple(out), base.dtype, base.origin)


def _eval_index(interp: ModuleInterp, node, env, depth):
    if isinstance(node, ast.Tuple):
        return tuple(_eval_index(interp, e, env, depth) for e in node.elts)
    if isinstance(node, ast.Slice):
        return slice(None)
    if isinstance(node, ast.Constant) and node.value is None:
        return NONE
    v = interp.eval(node, env, depth)
    if isinstance(v, NoneV):
        return NONE
    if isinstance(v, Poly):
        return v
    return v


def _subscript_impl(self: ModuleInterp, node: ast.Subscript, env, depth):
    base = self.eval(node.value, env, depth + 1)
    idx = _eval_index(self, node.slice, env, depth + 1)
    if isinstance(base, Arr):
        return _index_arr(base, idx)
    if isinstance(base, TupleV):
        if isinstance(idx, Poly) and idx.as_const() is not None:
            i = int(idx.as_const())
            if -len(base.items) <= i < len(base.items):
                return base.items[i]
        if isinstance(idx, slice):
            return TupleV(base.items[1:]) if _is_tail_slice(node.slice) \
                else UNKNOWN
        return UNKNOWN
    return UNKNOWN


def _is_tail_slice(sl) -> bool:
    return (isinstance(sl, ast.Slice) and sl.upper is None
            and sl.step is None and isinstance(sl.lower, ast.Constant)
            and sl.lower.value == 1)


ModuleInterp._eval_subscript = _subscript_impl


# -- envelope formula evaluation ------------------------------------------


@dataclasses.dataclass
class EnvelopeInfo:
    name: str
    bytes_poly: Optional[Poly]
    budget: Optional[float]
    failed: Optional[str] = None


def envelope_info(interp: ModuleInterp, fn: ast.AST,
                  bindings: Dict[str, Any]) -> EnvelopeInfo:
    """Evaluate a ``fits_*`` function to its (bytes polynomial, budget).
    Parameters bind to symbols by name (``<p>_itemsize`` to the operand
    itemsize atom); `bindings` pins values the kernel fixes."""
    env = interp.base_env()
    a = fn.args
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        nm = p.arg
        if nm in bindings:
            b = bindings[nm]
            env[nm] = Poly.const(b) if isinstance(b, (int, float)) else b
        elif nm.endswith("_itemsize"):
            env[nm] = Poly.of_atom(Atom("itemsize", nm[:-len("_itemsize")]))
        else:
            env[nm] = Poly.sym(nm)
    exec_ = _BodyExec(interp, env, 0)
    ret_expr = None
    try:
        for s in fn.body:
            if isinstance(s, ast.Return):
                ret_expr = s.value
                break
            if isinstance(s, ast.If):
                # domain gates (`if not (...): return False`) are not
                # byte charges — skipped
                continue
            exec_._stmt(s)
    except _Return:
        pass
    if ret_expr is None:
        return EnvelopeInfo(fn.name, None, None, "no return expression")
    cmp_node = _find_lte(ret_expr)
    if cmp_node is None:
        return EnvelopeInfo(fn.name, None, None,
                            "no `bytes <= budget` comparison in return")
    bytes_v = interp.eval(cmp_node.left, env, 0)
    budget_v = interp.eval(cmp_node.comparators[0], env, 0)
    if not isinstance(bytes_v, Poly):
        return EnvelopeInfo(fn.name, None, None,
                            "byte formula not symbolically evaluable")
    budget = budget_v.as_const() if isinstance(budget_v, Poly) else None
    return EnvelopeInfo(fn.name, bytes_v, budget)


def _find_lte(expr) -> Optional[ast.Compare]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], ast.LtE):
            return node
    return None


# -- registry + module analysis -------------------------------------------


def read_kernel_envelopes(module: Module) -> Optional[Dict[str, Tuple[str, Dict[str, Any]]]]:
    """The module's ``KERNEL_ENVELOPES`` literal dict, or None."""
    for node in module.tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "KERNEL_ENVELOPES"
                for t in node.targets):
            if not isinstance(node.value, ast.Dict):
                return {}
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    continue
                if isinstance(v, ast.Tuple) and len(v.elts) == 2 \
                        and isinstance(v.elts[0], ast.Constant):
                    bindings = {}
                    if isinstance(v.elts[1], ast.Dict):
                        for bk, bv in zip(v.elts[1].keys, v.elts[1].values):
                            if isinstance(bk, ast.Constant) \
                                    and isinstance(bv, ast.Constant):
                                bindings[bk.value] = bv.value
                    out[k.value] = (v.elts[0].value, bindings)
            return out
    return None


@dataclasses.dataclass
class ModuleAnalysis:
    interp: ModuleInterp
    registry: Optional[Dict[str, Tuple[str, Dict[str, Any]]]]
    #: wrapper name -> list of per-variant sites (None = analysis blew up)
    sites: Dict[str, List[KernelSite]]
    #: wrapper names that contain a pallas_call (syntactic)
    pallas_wrappers: List[str]


def analyze_module(module: Module) -> ModuleAnalysis:
    """Memoized per module tree: the full kernelcheck analysis."""
    cached = getattr(module.tree, "_kernelcheck", None)
    if cached is not None:
        return cached
    interp = ModuleInterp(module)
    registry = read_kernel_envelopes(module)
    pallas_wrappers = []
    sites: Dict[str, List[KernelSite]] = {}
    for name, fn in interp.functions.items():
        has = any(isinstance(n, ast.Call)
                  and terminal_name(n.func) == "pallas_call"
                  for n in ast.walk(fn))
        if not has:
            continue
        pallas_wrappers.append(name)
        try:
            sites[name] = extract_sites(interp, fn)
        except Exception:  # raftlint: disable=hygiene-bare-except
            sites[name] = []
    out = ModuleAnalysis(interp, registry, sites, sorted(pallas_wrappers))
    module.tree._kernelcheck = out
    return out


# -- concrete probe evaluation --------------------------------------------

#: probe geometries for the over-charge check: plausible on-chip shapes
#: (two points so a term linear in one symbol can't hide behind another)
PROBE_POINTS = (
    {"k": 100, "kbuf": 128, "bq": 128, "bn": 512, "chunk": 128, "L": 1024,
     "rot": 128, "d": 128, "m": 1024, "n": 65536, "bits": 4, "words": 4,
     "W": 4, "pw": 16, "ncb": 64, "n_lists": 64, "d_pad": 128,
     "m_pad": 1024, "n_pad": 65536},
    {"k": 10, "kbuf": 128, "bq": 128, "bn": 512, "chunk": 128, "L": 512,
     "rot": 256, "d": 96, "m": 256, "n": 8192, "bits": 8, "words": 8,
     "W": 8, "pw": 64, "ncb": 16, "n_lists": 16, "d_pad": 128,
     "m_pad": 256, "n_pad": 8192},
)


def probe_eval(interp: ModuleInterp, p: Poly, point: Dict[str, int],
               itemsizes: Dict[str, int]):
    """Concretely evaluate `p` at a probe point; unknown symbols fall
    back to 128, unknown itemsizes to 2. Raises CannotEval on opaque
    atoms that cannot be interpreted."""

    def env(kind: str, name: str):
        if kind == "sym":
            if name.startswith("__"):
                return 0
            return point.get(name, 128)
        return itemsizes.get(name, 2)

    def resolver(fn_node, name: str, vals: list):
        fn = fn_node or interp.functions.get(name)
        if fn is None:
            raise CannotEval(f"cannot interpret call to {name}")
        local = interp.base_env()
        a = fn.args
        params = a.posonlyargs + a.args
        for prm, v in zip(params, vals):
            local[prm.arg] = Poly.const(v)
        interp.bind_params(fn, local, [Poly.const(v) for v in vals], {})
        exec_ = _BodyExec(interp, local, 0)
        exec_.run(fn.body)
        if isinstance(exec_.retval, Poly):
            c = exec_.retval.concrete(env, resolver)
            return c
        raise CannotEval(f"{name} did not return a numeric value")

    return p.concrete(env, resolver)
