"""raftlint: AST-based static analysis for raft_tpu's layer contracts.

The library's reusability story rests on invariants the interpreter
never checks: traced code must be host-free (bit-identity of the
failover paths depends on it), threaded subsystems must touch shared
state under their lock, every chaos injection site must stay registered
in ``core.faults.FAULT_SITES``, and the subpackage import DAG must stay
acyclic and layered. ``ci/check_style.sh`` used to approximate a subset
of this with greps; raftlint replaces those with scope-aware AST rules.

Since raftlint 2.0 the suite is flow-sensitive: per-function CFGs with
dominance/control-dependence (:mod:`tools.raftlint.cfg`) and a
project-wide call graph with bounded interprocedural summaries and
rank-taint (:mod:`tools.raftlint.project`) drive the SPMD
``collective-divergence``/``collective-order`` rules, the
``lock-order-deadlock`` cycle check, and the ``commit-ordering``
(cursor-written-LAST) check — still stdlib ``ast`` only.

raftlint 3.0 adds the kernelcheck engine
(:mod:`tools.raftlint.kernels`): an abstract shape/dtype/VMEM
interpreter over ``pl.pallas_call`` sites driving
``kernel-vmem-envelope`` (fits_* formulas cross-checked monomial by
monomial against the bytes each kernel actually allocates),
``kernel-blockspec-consistency`` (index_map arity vs grid rank +
scalar prefetch, block/out ranks, final-store dtypes),
``kernel-dtype-flow`` (MXU bf16/int8 discipline, unsigned popcounts)
and ``dispatch-envelope-guard`` (every fused call site under its
envelope validation) — plus ``tuned-key-registry`` pinning every
measured-dispatch key to the machine-readable
``core.tuned.TUNED_KEYS``.

Usage::

    python -m tools.raftlint [--json] [--changed [BASE]] [paths...]

Programmatic entry points live in :mod:`tools.raftlint.engine`
(``lint_paths``); rules register themselves on import of
:mod:`tools.raftlint.rules`. See docs/linting.md for the rule catalog,
the analysis core, the per-line pragma
(``# raftlint: disable=<rule>``) and the baseline workflow.
"""

from tools.raftlint.engine import (  # noqa: F401
    Finding,
    LintResult,
    lint_paths,
    registered_rules,
)
from tools.raftlint import rules as _rules  # noqa: F401  (registers rules)
