"""Rule modules register themselves on import (via the decorators in
tools.raftlint.engine). Importing this package loads the full rule set;
add new rule modules to the list below and to docs/linting.md."""

from tools.raftlint.rules import (  # noqa: F401
    collectives,
    commit_order,
    fault_sites,
    hygiene,
    kernelcheck,
    layers,
    locks,
    statecheck,
    threadcheck,
    trace_safety,
    tuned_keys,
)
