"""Lock rules for the threaded subsystems (serve/, obs/, jobs/,
core/resources.py, ...): the PR-5 lock-discipline race detector, plus
the raftlint 2.0 ``lock-order-deadlock`` cycle check over the
cross-class lock-acquisition graph.

Classes that create a ``threading.Lock``/``RLock``/``Condition`` are
declaring "my mutable state is shared". For such a class, any instance
attribute that is *written while holding the lock* somewhere (outside
``__init__``) is treated as lock-guarded; every other access to it that
does not hold the lock is a candidate race and gets flagged. ``__init__``
is exempt (the instance is not published yet).

This is intentionally a *discipline* check, not a proof: it can't see
``acquire()``/``release()`` pairs, cross-object locking, or attributes
guarded by a different lock than the one held (any of the class's locks
counts as "held"). Methods named ``*_locked`` are treated as holding
the lock throughout — that suffix is the library's caller-holds-the-lock
naming convention, and the linter is what keeps it honest-by-default.
Nested functions and lambdas are analyzed as lock-free even when
defined inside a ``with self._lock`` block: they usually escape (worker
threads, callbacks) and run after the lock is gone. Lock-free fast paths that are genuinely safe
(immutable after publication, or delegating to an instrument that
carries its own lock) should carry a justified
``# raftlint: disable=lock-discipline`` pragma — the pragma is the
documentation that someone *decided* the access is safe.

Scope: raft_tpu/.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Set

from tools.raftlint.engine import (
    Finding,
    Module,
    project_rule,
    rule,
    terminal_name,
)

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass
class _Access:
    attr: str
    method: str
    store: bool
    under_lock: bool
    line: int
    col: int


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attribute names assigned a threading.Lock/RLock/Condition
    anywhere in the class body (typically in __init__)."""
    names: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if terminal_name(node.value.func) in LOCK_FACTORIES:
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        names.add(tgt.attr)
    return names


def _is_self_lock(expr: ast.AST, locks: Set[str]) -> bool:
    return (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in locks)


def _collect_accesses(method: ast.FunctionDef, locks: Set[str]) -> List[_Access]:
    out: List[_Access] = []

    def visit(node: ast.AST, depth: int) -> None:
        if isinstance(node, ast.With):
            held = depth + sum(
                1 for item in node.items
                if _is_self_lock(item.context_expr, locks))
            for item in node.items:
                visit(item.context_expr, depth)
            for stmt in node.body:
                visit(stmt, held)
            return
        if isinstance(node, _FUNCS + (ast.Lambda,)):
            # nested defs/lambdas run later, possibly on another thread
            # and without the lock — analyze them as lock-free context
            for child in ast.iter_child_nodes(node):
                visit(child, 0)
            return
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr not in locks):
            out.append(_Access(
                attr=node.attr,
                method=method.name,
                store=isinstance(node.ctx, (ast.Store, ast.Del)),
                under_lock=depth > 0,
                line=node.lineno,
                col=node.col_offset + 1,
            ))
        for child in ast.iter_child_nodes(node):
            visit(child, depth)

    # the `_locked` suffix is the library's caller-holds-the-lock naming
    # convention (e.g. MicroBatcher._take_locked): analyze such methods
    # as if the lock were held throughout
    base_depth = 1 if method.name.endswith("_locked") else 0
    for stmt in method.body:
        visit(stmt, base_depth)
    return out


@rule(
    "lock-discipline",
    "attribute written under the class lock elsewhere but accessed "
    "without it here",
    "raft_tpu/",
)
def check_lock_discipline(module: Module) -> Iterator[Finding]:
    if not module.path.startswith("raft_tpu/"):
        return
    for cls in ast.walk(module.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs(cls)
        if not locks:
            continue
        accesses: List[_Access] = []
        for item in cls.body:
            if isinstance(item, _FUNCS) and item.name != "__init__":
                accesses.extend(_collect_accesses(item, locks))
        guarded: Dict[str, str] = {}  # attr -> first guarding method
        for a in accesses:
            if a.store and a.under_lock and a.attr not in guarded:
                guarded[a.attr] = a.method
        for a in accesses:
            if a.attr in guarded and not a.under_lock:
                yield Finding(
                    module.path, a.line, a.col, "lock-discipline",
                    f"'{cls.name}.{a.attr}' is written under the lock in "
                    f"{guarded[a.attr]}() but accessed without it in "
                    f"{a.method}()")


# -- lock-order deadlock (raftlint 2.0, interprocedural) -----------------
#
# Deadlock by lock-order inversion needs two locks and two threads:
# thread 1 holds A and wants B while thread 2 holds B and wants A. The
# static shadow of that bug is a CYCLE in the lock-acquisition graph —
# nodes are (class, lock attribute), and an edge A -> B means "somewhere,
# B is acquired while A is held", either directly (``with self._a: ...
# with self._b:``) or through a call whose (transitive, bounded) summary
# acquires B. serve/obs/jobs each hold multiple locks and call across
# class boundaries (batcher -> metrics -> registry), which is exactly
# where a by-hand ordering convention silently rots.
#
# Also flagged: re-acquiring a NON-reentrant ``threading.Lock`` that is
# already held (a self-edge) — that one deadlocks a single thread, no
# partner needed. RLock/Condition self-edges are re-entrant and exempt.
#
# Bounded resolution: ``self.m()`` resolves within the class; module
# functions through imports; ``obj.m()`` falls back to every project
# class method of that name, but a by-name fallback never contributes
# edges onto the *holder's own class* locks (per-instance locks of
# sibling instances are not self-deadlocks — only exact ``self`` calls
# may close a same-class edge).


def _method_held_seed(method: ast.AST, cls_locks: Set[str]):
    """``*_locked`` methods run with "the" class lock held — when the
    class has exactly one lock, that lock seeds the held set."""
    if method.name.endswith("_locked") and len(cls_locks) == 1:
        return [next(iter(cls_locks))]
    return []


def _edge_events(method: ast.FunctionDef, cls_qname: str,
                 cls_locks: Set[str], module_path: str):
    """(held, kind, payload, line, col) events in source order:
    kind='acquire' payload=lock attr; kind='call' payload=Call node.
    Nested defs/lambdas escape the lock context and are analyzed
    lock-free (matching lock-discipline)."""
    events = []

    def visit(node, held):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = list(held)
            for item in node.items:
                e = item.context_expr
                attr = None
                if (isinstance(e, ast.Attribute)
                        and isinstance(e.value, ast.Name)
                        and e.value.id == "self" and e.attr in cls_locks):
                    attr = e.attr
                if attr is not None:
                    events.append((tuple(new_held), "acquire", attr,
                                   e.lineno, e.col_offset + 1))
                    new_held.append(attr)
                else:
                    visit(e, held)
            for stmt in node.body:
                visit(stmt, new_held)
            return
        if isinstance(node, _FUNCS + (ast.Lambda,)):
            for child in ast.iter_child_nodes(node):
                visit(child, [])
            return
        if isinstance(node, ast.Call) and held:
            events.append((tuple(held), "call", node,
                           node.lineno, node.col_offset + 1))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    seed = _method_held_seed(method, cls_locks)
    for stmt in method.body:
        visit(stmt, seed)
    return events


def _project_rule_lock_order(modules, repo_root):
    from tools.raftlint.engine import terminal_name as _tn
    from tools.raftlint.project import project_index

    index = project_index(modules)
    # (held_node, acquired_node) -> sorted list of sites
    edges: Dict[tuple, List[tuple]] = {}
    factories: Dict[tuple, str] = {}

    def note(held, acq, path, line, col, via):
        edges.setdefault((held, acq), []).append((path, line, col, via))

    for cls_qname in sorted(index.classes):
        info = index.classes[cls_qname]
        if not info.locks or not info.module.startswith("raft_tpu/"):
            continue
        for attr, factory in info.locks.items():
            factories[(cls_qname, attr)] = factory
        for mname in sorted(info.methods):
            method = info.methods[mname]
            for held, kind, payload, line, col in _edge_events(
                    method, cls_qname, set(info.locks), info.module):
                held_nodes = [(cls_qname, h) for h in held]
                if kind == "acquire":
                    acq = (cls_qname, payload)
                    for h in held_nodes:
                        note(h, acq, info.module, line, col,
                             f"{info.name}.{mname}")
                else:
                    call = payload
                    exact = index.resolve_call(info.module, call.func,
                                               cls=cls_qname)
                    by_name = []
                    if not exact and isinstance(call.func, ast.Attribute):
                        # by-name fallback ONLY for project-unique method
                        # names: common names (`clear`, `reset`) also live
                        # on builtin containers and many classes — a union
                        # would fabricate a dense graph of false cycles
                        hits = index.resolve_methods_by_name(_tn(call.func))
                        if len(hits) == 1:
                            by_name = hits
                    for q in exact + by_name:
                        s = index.summaries.get(q)
                        if s is None or not s.acquires:
                            continue
                        for acq in sorted(s.acquires):
                            if q in by_name and acq[0] == cls_qname:
                                # sibling-instance lock of our own class:
                                # not provably the same object
                                continue
                            for h in held_nodes:
                                note(h, acq, info.module, line, col,
                                     f"{info.name}.{mname} -> "
                                     f"{index.functions[q].name}()")

    # reachability closure for cycle membership
    adj: Dict[tuple, Set[tuple]] = {}
    for (u, v) in edges:
        adj.setdefault(u, set()).add(v)

    def reaches(src, dst) -> bool:
        seen = set()
        stack = [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(adj.get(n, ()))
        return False

    def short(node) -> str:
        cls_q, attr = node
        return f"{cls_q.split('::')[-1]}.{attr}"

    for (u, v) in sorted(edges):
        sites = sorted(set(edges[(u, v)]))
        if u == v:
            if factories.get(u) == "Lock":
                for path, line, col, via in sites:
                    yield Finding(
                        path, line, col, "lock-order-deadlock",
                        f"re-acquiring non-reentrant {short(u)} while "
                        f"already held (via {via}): deadlocks the "
                        f"acquiring thread itself — use an RLock or an "
                        f"*_locked variant")
            continue
        if reaches(v, u):
            for path, line, col, via in sites:
                yield Finding(
                    path, line, col, "lock-order-deadlock",
                    f"acquiring {short(v)} while holding {short(u)} "
                    f"(via {via}) closes a lock-order cycle "
                    f"{short(u)} -> {short(v)} ~> {short(u)}: two "
                    f"threads acquiring in opposite orders deadlock — "
                    f"fix one side's order or drop to a single lock")


check_lock_order_deadlock = project_rule(
    "lock-order-deadlock",
    "cycle in the cross-class lock-acquisition graph (lock-order "
    "inversion deadlock), interprocedural via bounded call summaries",
    "raft_tpu/",
)(_project_rule_lock_order)
