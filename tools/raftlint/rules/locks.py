"""Lock-discipline rule: a lightweight race detector for the threaded
subsystems (serve/, obs/, core/resources.py, ...).

Classes that create a ``threading.Lock``/``RLock``/``Condition`` are
declaring "my mutable state is shared". For such a class, any instance
attribute that is *written while holding the lock* somewhere (outside
``__init__``) is treated as lock-guarded; every other access to it that
does not hold the lock is a candidate race and gets flagged. ``__init__``
is exempt (the instance is not published yet).

This is intentionally a *discipline* check, not a proof: it can't see
``acquire()``/``release()`` pairs, cross-object locking, or attributes
guarded by a different lock than the one held (any of the class's locks
counts as "held"). Methods named ``*_locked`` are treated as holding
the lock throughout — that suffix is the library's caller-holds-the-lock
naming convention, and the linter is what keeps it honest-by-default.
Nested functions and lambdas are analyzed as lock-free even when
defined inside a ``with self._lock`` block: they usually escape (worker
threads, callbacks) and run after the lock is gone. Lock-free fast paths that are genuinely safe
(immutable after publication, or delegating to an instrument that
carries its own lock) should carry a justified
``# raftlint: disable=lock-discipline`` pragma — the pragma is the
documentation that someone *decided* the access is safe.

Scope: raft_tpu/.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Set

from tools.raftlint.engine import Finding, Module, rule, terminal_name

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass
class _Access:
    attr: str
    method: str
    store: bool
    under_lock: bool
    line: int
    col: int


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attribute names assigned a threading.Lock/RLock/Condition
    anywhere in the class body (typically in __init__)."""
    names: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if terminal_name(node.value.func) in LOCK_FACTORIES:
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        names.add(tgt.attr)
    return names


def _is_self_lock(expr: ast.AST, locks: Set[str]) -> bool:
    return (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in locks)


def _collect_accesses(method: ast.FunctionDef, locks: Set[str]) -> List[_Access]:
    out: List[_Access] = []

    def visit(node: ast.AST, depth: int) -> None:
        if isinstance(node, ast.With):
            held = depth + sum(
                1 for item in node.items
                if _is_self_lock(item.context_expr, locks))
            for item in node.items:
                visit(item.context_expr, depth)
            for stmt in node.body:
                visit(stmt, held)
            return
        if isinstance(node, _FUNCS + (ast.Lambda,)):
            # nested defs/lambdas run later, possibly on another thread
            # and without the lock — analyze them as lock-free context
            for child in ast.iter_child_nodes(node):
                visit(child, 0)
            return
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr not in locks):
            out.append(_Access(
                attr=node.attr,
                method=method.name,
                store=isinstance(node.ctx, (ast.Store, ast.Del)),
                under_lock=depth > 0,
                line=node.lineno,
                col=node.col_offset + 1,
            ))
        for child in ast.iter_child_nodes(node):
            visit(child, depth)

    # the `_locked` suffix is the library's caller-holds-the-lock naming
    # convention (e.g. MicroBatcher._take_locked): analyze such methods
    # as if the lock were held throughout
    base_depth = 1 if method.name.endswith("_locked") else 0
    for stmt in method.body:
        visit(stmt, base_depth)
    return out


@rule(
    "lock-discipline",
    "attribute written under the class lock elsewhere but accessed "
    "without it here",
    "raft_tpu/",
)
def check_lock_discipline(module: Module) -> Iterator[Finding]:
    if not module.path.startswith("raft_tpu/"):
        return
    for cls in ast.walk(module.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs(cls)
        if not locks:
            continue
        accesses: List[_Access] = []
        for item in cls.body:
            if isinstance(item, _FUNCS) and item.name != "__init__":
                accesses.extend(_collect_accesses(item, locks))
        guarded: Dict[str, str] = {}  # attr -> first guarding method
        for a in accesses:
            if a.store and a.under_lock and a.attr not in guarded:
                guarded[a.attr] = a.method
        for a in accesses:
            if a.attr in guarded and not a.under_lock:
                yield Finding(
                    module.path, a.line, a.col, "lock-discipline",
                    f"'{cls.name}.{a.attr}' is written under the lock in "
                    f"{guarded[a.attr]}() but accessed without it in "
                    f"{a.method}()")
