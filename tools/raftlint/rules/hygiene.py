"""Hygiene rules: AST-scoped ports of the old ci/check_style.sh greps,
plus typed-exception and float64-drift enforcement.

The greps could not see scope: a ``time.time()`` in a test was as fatal
as one in a latency ring, and a quoted ``"wb"`` inside a docstring
tripped the raw-write gate. As AST rules each check carries its real
scope:

  hygiene-bare-except    raft_tpu/, bench/ — a bare ``except:`` swallows
                         KeyboardInterrupt/SystemExit and masks genuine
                         faults; the resilience layer depends on
                         failures surfacing typed.
  hygiene-wallclock      raft_tpu/, bench/ — ``time.time()`` jumps under
                         NTP steps and breaks span/latency accounting;
                         use time.monotonic()/perf_counter(). Tests may
                         use it for coarse assertions.
  hygiene-raw-write      raft_tpu/ except core/serialize.py — checkpoint
                         writes must ride the atomic
                         write-to-temp-then-rename helper with CRC-32C
                         checksums; bare ``os.rename``/``os.replace`` or
                         ``open(.., "wb")`` bypasses both.
  hygiene-untyped-raise  raft_tpu/ — ``raise Exception/RuntimeError``
                         gives callers nothing to catch; raise one of
                         the library's typed errors (SerializationError,
                         RecoveryError, ...) so retry/recovery policy
                         can discriminate.
  hygiene-float64        raft_tpu/ — x64 is off; a float64 dtype handed
                         to jax/jnp silently truncates to float32 (or
                         flips behavior if someone enables x64), so
                         jnp.float64 and float64 dtype= arguments in
                         jnp/jax calls are drift. Host-side NumPy
                         float64 (metric rings, linkage deltas) is fine
                         and not flagged.
  hygiene-obs-torn-write raft_tpu/obs/ — obs snapshot/dump writers
                         (export.save_snapshot, flight dumps) must open
                         their output through a ``with atomic_write(p)
                         as tmp:`` binding; a text-mode truncating
                         open() on any other path can tear exactly on
                         the crash the flight recorder exists for.
                         Append modes are exempt (the JSONL ledger is
                         an append-only log, not a snapshot).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from tools.raftlint.engine import (
    Finding,
    Module,
    dotted_chain,
    rule,
)

_LIB = ("raft_tpu/",)
_LIB_BENCH = ("raft_tpu/", "bench/")

RAW_WRITE_EXEMPT = {"raft_tpu/core/serialize.py"}
WRITE_MODES = {"wb", "bw", "w+b", "bw+", "xb", "bx", "ab", "ba"}
UNTYPED = {"Exception", "RuntimeError"}


@rule("hygiene-bare-except",
      "bare 'except:' (swallows KeyboardInterrupt/SystemExit)",
      "raft_tpu/, bench/")
def check_bare_except(module: Module) -> Iterator[Finding]:
    if not module.path.startswith(_LIB_BENCH):
        return
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            yield Finding(
                module.path, node.lineno, node.col_offset + 1,
                "hygiene-bare-except",
                "bare 'except:' swallows KeyboardInterrupt/SystemExit and "
                "masks genuine faults; catch a concrete exception type")


@rule("hygiene-wallclock",
      "time.time() in library/bench timing code",
      "raft_tpu/, bench/ (tests exempt)")
def check_wallclock(module: Module) -> Iterator[Finding]:
    if not module.path.startswith(_LIB_BENCH):
        return
    for node in ast.walk(module.tree):
        if (isinstance(node, ast.Call)
                and dotted_chain(node.func) == ("time", "time")):
            yield Finding(
                module.path, node.lineno, node.col_offset + 1,
                "hygiene-wallclock",
                "time.time() jumps under NTP steps; use time.monotonic() "
                "or time.perf_counter() for timing")


def _open_mode(call: ast.Call) -> Optional[str]:
    """The write mode string of an open-like call, wherever it sits:
    mode= keyword, open(path, mode) second positional, or
    Path(p).open(mode) FIRST positional — matching is exact against
    WRITE_MODES, so a filename in slot 0 can't false-positive."""
    for kw in call.keywords:
        if kw.arg == "mode":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return v.value
            return None
    for a in call.args[:2]:
        if (isinstance(a, ast.Constant) and isinstance(a.value, str)
                and a.value in WRITE_MODES):
            return a.value
    return None


@rule("hygiene-raw-write",
      "bare os.rename/os.replace/open(.., 'wb') outside core.serialize",
      "raft_tpu/ except core/serialize.py")
def check_raw_write(module: Module) -> Iterator[Finding]:
    if not module.path.startswith(_LIB) or module.path in RAW_WRITE_EXEMPT:
        return
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = dotted_chain(node.func)
        if chain in (("os", "rename"), ("os", "replace")):
            yield Finding(
                module.path, node.lineno, node.col_offset + 1,
                "hygiene-raw-write",
                f"bare {'.'.join(chain)}() in the library; route "
                f"checkpoint writes through core.serialize.atomic_write "
                f"(temp-then-rename + CRC-32C checksums)")
        elif chain and chain[-1] == "open":
            # bare open() and attribute opens alike (gzip.open, io.open,
            # Path.open) — the grep this rule replaced caught them all
            mode = _open_mode(node)
            if mode in WRITE_MODES:
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "hygiene-raw-write",
                    f"{'.'.join(chain)}(.., {mode!r}) in the library; "
                    f"binary container writes must ride "
                    f"core.serialize.atomic_write so a crash mid-write "
                    f"never leaves a torn file")


@rule("hygiene-untyped-raise",
      "raise Exception/RuntimeError without a typed subclass",
      "raft_tpu/")
def check_untyped_raise(module: Module) -> Iterator[Finding]:
    if not module.path.startswith(_LIB):
        return
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Raise) or node.exc is None:
            continue
        exc = node.exc
        name = None
        if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
            name = exc.func.id
        elif isinstance(exc, ast.Name):
            name = exc.id
        if name in UNTYPED:
            yield Finding(
                module.path, node.lineno, node.col_offset + 1,
                "hygiene-untyped-raise",
                f"raise {name} gives callers nothing to catch; raise a "
                f"typed library error (see core.serialize / "
                f"comms.recovery for the idiom)")


_OBS = ("raft_tpu/obs/",)
# truncating text write modes (binary is hygiene-raw-write's job;
# append is the ledger's legitimate JSONL idiom — a torn FINAL line is
# recoverable, a torn whole-file snapshot is not)
TEXT_WRITE_MODES = {"w", "wt", "tw", "w+", "+w", "wt+", "w+t", "x", "xt",
                    "tx", "x+", "+x"}


def _atomic_write_names(tree: ast.AST) -> set:
    """Names bound by ``with atomic_write(...) as NAME`` (any import
    spelling whose call chain ends in atomic_write)."""
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            ctx = item.context_expr
            if (isinstance(ctx, ast.Call)
                    and (dotted_chain(ctx.func) or ())[-1:]
                    == ("atomic_write",)
                    and isinstance(item.optional_vars, ast.Name)):
                names.add(item.optional_vars.id)
    return names


@rule("hygiene-obs-torn-write",
      "truncating text open() in obs/ not routed through atomic_write",
      "raft_tpu/obs/")
def check_obs_torn_write(module: Module) -> Iterator[Finding]:
    if not module.path.startswith(_OBS):
        return
    atomic = _atomic_write_names(module.tree)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = dotted_chain(node.func)
        if not chain or chain[-1] != "open":
            continue
        mode = None
        for kw in node.keywords:
            if kw.arg == "mode":
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    mode = v.value
        if mode is None:
            for a in node.args[1:2]:
                if (isinstance(a, ast.Constant) and isinstance(a.value, str)
                        and a.value in TEXT_WRITE_MODES):
                    mode = a.value
        if mode not in TEXT_WRITE_MODES:
            continue
        target = node.args[0] if node.args else None
        if isinstance(target, ast.Name) and target.id in atomic:
            continue  # writing INTO an atomic_write temp binding
        yield Finding(
            module.path, node.lineno, node.col_offset + 1,
            "hygiene-obs-torn-write",
            f"{'.'.join(chain)}(.., {mode!r}) in obs/ writes a snapshot "
            f"that can tear mid-crash; bind the path with "
            f"`with atomic_write(path) as tmp:` and open the TMP name "
            f"(append-mode logs are exempt)")


def _is_float64(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value in ("float64", "f8"):
        return True
    chain = dotted_chain(node)
    return chain is not None and chain[-1] == "float64"


@rule("hygiene-float64",
      "float64 dtype reaching jax/jnp (x64 is off)",
      "raft_tpu/")
def check_float64(module: Module) -> Iterator[Finding]:
    if not module.path.startswith(_LIB):
        return
    flagged_dtype_nodes = set()
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = dotted_chain(node.func) or ()
        jaxish = chain[:1] in (("jnp",), ("jax",), ("lax",))
        if jaxish:
            for kw in node.keywords:
                if kw.arg == "dtype" and _is_float64(kw.value):
                    flagged_dtype_nodes.add(id(kw.value))
                    yield Finding(
                        module.path, kw.value.lineno, kw.value.col_offset + 1,
                        "hygiene-float64",
                        f"float64 dtype passed to {'.'.join(chain)}(): x64 "
                        f"is off, jax silently truncates to float32 — use "
                        f"float32 explicitly (host-side NumPy float64 is "
                        f"fine)")
    # jnp.float64 mentioned anywhere else (astype args, dtype aliases,
    # ...); nodes already reported as a dtype= argument are skipped
    for node in ast.walk(module.tree):
        if id(node) in flagged_dtype_nodes:
            continue
        if dotted_chain(node) == ("jnp", "float64"):
            yield Finding(
                module.path, node.lineno, node.col_offset + 1,
                "hygiene-float64",
                "jnp.float64 in library code: x64 is off, this resolves "
                "to float32 at best — name the dtype you mean")
