"""Fault-site drift rules: injection call sites and the FAULT_SITES
registry must agree, both ways.

The chaos story (core/faults.py, ci/test.sh chaos) only audits what it
knows about: a fault site referenced in code but missing from
``core.faults.FAULT_SITES`` is invisible to drills and docs
(``fault-site-unknown``), and a registered site that no code references
is a drill that silently stopped covering anything
(``fault-site-unused``). Site strings are collected from:

  - calls to the injection hooks (``fault_point``, ``corrupt_host``,
    ``corrupt_in_trace``, ``drop_contribution``, ``corrupt_file``) and
    to the plan query helpers (``active_for``, ``matching``,
    ``killed_ranks``) — first positional argument or ``site=``;
  - ``Fault(...)`` constructions (``site=`` keyword or second
    positional);
  - module-level ``<NAME>_SITE = "literal"`` constants (the idiom for
    passing a site to a hook by name).

Glob site patterns (``resilience.*``) are fine as long as they match at
least one registered site. The registry itself is read from
``raft_tpu/core/faults.py`` *by AST* — the linter never imports
raft_tpu (that would drag jax in). The unused check runs only on
whole-package scans (the raft_tpu package root in the scan set): the
hooks are spread across comms/, serve/ and neighbors/, so a
subdirectory lint has no basis to call a site dead.

Scope: raft_tpu/, bench/, tests/ (drills included on purpose: a test
drilling an unregistered site is exactly the drift this rule exists
to catch; purely synthetic plan-mechanics sites carry a justified
pragma).
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import os
import re
from typing import Dict, Iterator, List, Optional, Tuple

from tools.raftlint.engine import (
    Finding,
    Module,
    const_str,
    load_module,
    project_rule,
    terminal_name,
)

HOOKS = {"fault_point", "corrupt_host", "corrupt_in_trace",
         "drop_contribution", "corrupt_file", "maybe_inject", "_inject"}
QUERIES = {"active_for", "matching", "killed_ranks"}
SITE_CONST_RE = re.compile(r"^[A-Z0-9_]*_SITE$")
GLOB_CHARS = ("*", "?", "[")

REGISTRY_RELPATH = "raft_tpu/core/faults.py"


def _in_scope(path: str) -> bool:
    return path.startswith(("raft_tpu/", "bench/", "tests/"))


@dataclasses.dataclass
class _SiteRef:
    site: str
    path: str
    line: int
    col: int
    context: str


def _site_arg(call: ast.Call, positional_index: int) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "site":
            return kw.value
    if len(call.args) > positional_index:
        return call.args[positional_index]
    return None


def collect_site_refs(module: Module) -> List[_SiteRef]:
    refs: List[_SiteRef] = []
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            name = terminal_name(node.func)
            expr = None
            if name in HOOKS or name in QUERIES:
                expr = _site_arg(node, 0)
            elif name == "Fault":
                expr = _site_arg(node, 1)
            site = const_str(expr) if expr is not None else None
            if site is not None:
                refs.append(_SiteRef(site, module.path, expr.lineno,
                                     expr.col_offset + 1, name))
        elif isinstance(node, ast.Assign) and const_str(node.value) is not None:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and SITE_CONST_RE.match(tgt.id):
                    refs.append(_SiteRef(
                        const_str(node.value), module.path,
                        node.value.lineno, node.value.col_offset + 1,
                        tgt.id))
    return refs


def load_registry(modules, repo_root) -> Tuple[Dict[str, Tuple[int, int]], Optional[str]]:
    """FAULT_SITES keys with their (line, col) source positions, read
    from the scanned module set or, failing that, from disk."""
    reg_mod = next((m for m in modules if m.path == REGISTRY_RELPATH), None)
    if reg_mod is None:
        abspath = os.path.join(repo_root, REGISTRY_RELPATH)
        if os.path.exists(abspath):
            reg_mod, _err = load_module(abspath, repo_root)
    if reg_mod is None:
        return {}, None
    for node in ast.walk(reg_mod.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "FAULT_SITES"
                for t in node.targets):
            if isinstance(node.value, ast.Dict):
                out: Dict[str, Tuple[int, int]] = {}
                for key in node.value.keys:
                    site = const_str(key)
                    if site is not None:
                        out[site] = (key.lineno, key.col_offset + 1)
                return out, reg_mod.path
    return {}, reg_mod.path


@project_rule(
    "fault-site-unknown",
    "site literal passed to an injection hook is not in "
    "core.faults.FAULT_SITES (or the registry itself is unparseable)",
    "raft_tpu/, bench/, tests/",
)
def check_unknown_sites(modules, repo_root) -> Iterator[Finding]:
    registry, src_path = load_registry(modules, repo_root)
    all_refs = [ref for module in modules if _in_scope(module.path)
                for ref in collect_site_refs(module)]
    if not registry:
        # fail CLOSED: injection hooks exist but the registry is gone or
        # no longer a literal dict — the drift gate must not silently
        # turn green while policing nothing
        if all_refs:
            anchor = src_path or all_refs[0].path
            yield Finding(
                anchor, 1, 1, "fault-site-unknown",
                f"FAULT_SITES registry missing or not a literal dict "
                f"assignment in {REGISTRY_RELPATH} — site literals exist "
                f"but cannot be checked; restore the literal dict")
        return
    for ref in all_refs:
        if any(c in ref.site for c in GLOB_CHARS):
            if not fnmatch.filter(sorted(registry), ref.site):
                yield Finding(
                    ref.path, ref.line, ref.col, "fault-site-unknown",
                    f"site glob {ref.site!r} (via {ref.context}) matches "
                    f"no registered fault site")
        elif ref.site not in registry:
            yield Finding(
                ref.path, ref.line, ref.col, "fault-site-unknown",
                f"site {ref.site!r} (via {ref.context}) is not in "
                f"core.faults.FAULT_SITES — register it or fix the name")


@project_rule(
    "fault-site-unused",
    "FAULT_SITES entry never referenced by any injection hook or drill",
    "registry vs raft_tpu/, bench/, tests/",
)
def check_unused_sites(modules, repo_root) -> Iterator[Finding]:
    registry, src_path = load_registry(modules, repo_root)
    if not registry or src_path is None:
        return
    # only meaningful on a whole-package scan: the hooks live across
    # comms/, serve/, neighbors/ — linting a subdirectory (or a lone
    # file) must not declare every site unused. "Whole package" is
    # detected by the package root being in the scan set.
    scanned = {m.path for m in modules}
    if REGISTRY_RELPATH not in scanned or "raft_tpu/__init__.py" not in scanned:
        return
    used = set()
    for module in modules:
        if not _in_scope(module.path):
            continue
        for ref in collect_site_refs(module):
            if any(c in ref.site for c in GLOB_CHARS):
                used.update(fnmatch.filter(sorted(registry), ref.site))
            else:
                used.add(ref.site)
    for site in sorted(registry):
        if site not in used:
            line, col = registry[site]
            yield Finding(
                src_path, line, col, "fault-site-unused",
                f"registered fault site {site!r} has no live injection "
                f"hook or drill referencing it — dead registry entry")
