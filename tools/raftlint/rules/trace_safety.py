"""Trace-safety rules: traced code must be host-free and deterministic.

Everything staged into ``jax.jit`` / ``pjit`` / ``jax.shard_map`` /
``pl.pallas_call`` runs at *trace* time once and then replays as a
compiled program: host side effects (``time.*``, ``print``) fire at the
wrong time or never; module-level RNG (``random.*`` / ``np.random.*``)
bakes one draw into the compiled artifact, silently breaking the
bit-identity guarantees the replication failover path depends on; and
host syncs (``.item()``, ``float(arg)`` on a traced argument) either
fail under tracing or serialize the device pipeline (TPU-KNN's peak
throughput argument: the search loop must be fully compiled and
host-free). ``try/except`` around ``lax`` ops is a related trap: traced
ops don't raise at run time, so the handler is dead code that suggests
error handling that doesn't exist.

A function is considered *traced* when (a) a decorator mentions one of
the tracer entry points (including through ``functools.partial``),
(b) its name (or a lambda) is passed to a tracer call anywhere in the
same module, or (c) it is lexically nested inside a traced function.
Parameters declared static (``static_argnames``/``static_argnums``
literals) are Python values at trace time and exempt from the host-sync
check. Helpers traced only via cross-module indirection are out of
scope (an AST linter can't see them) — keep kernel bodies next to their
tracer.

Scope: raft_tpu/ and bench/. Tests are exempt: they intentionally
build hostile traced functions to assert library behavior.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from tools.raftlint.engine import (
    Finding,
    Module,
    dotted_chain,
    rule,
    terminal_name,
)

TRACERS = {"jit", "pjit", "shard_map", "pallas_call"}

#: host-effect module roots: any ``<root>.<attr>(...)`` call inside
#: traced code is flagged (time.monotonic is as wrong as time.time here)
HOST_EFFECT_ROOTS = {"time", "os", "datetime"}

HOST_SYNC_BUILTINS = {"float", "int", "bool"}
HOST_SYNC_METHODS = {"item", "tolist"}


def _in_scope(path: str) -> bool:
    return path.startswith("raft_tpu/") or path.startswith("bench/")


def _mentions_tracer(node: ast.AST) -> bool:
    return any(terminal_name(n) in TRACERS
               for n in ast.walk(node)
               if isinstance(n, (ast.Name, ast.Attribute)))


_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _static_arg_spec(call: ast.Call):
    """(names, nums) declared static on a jit/pjit call: literal strings
    from static_argnames, literal ints from static_argnums."""
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in call.keywords:
        values = ()
        if isinstance(kw.value, (ast.Tuple, ast.List)):
            values = kw.value.elts
        elif isinstance(kw.value, ast.Constant):
            values = (kw.value,)
        if kw.arg == "static_argnames":
            names.update(v.value for v in values
                         if isinstance(v, ast.Constant)
                         and isinstance(v.value, str))
        elif kw.arg == "static_argnums":
            nums.update(v.value for v in values
                        if isinstance(v, ast.Constant)
                        and isinstance(v.value, int))
    return names, nums


def _positional_params(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]


def _collect_traced(tree: ast.AST):
    """Function/lambda nodes considered traced (mapped to their declared
    static parameter names), with lexical-nesting propagation. Memoized
    on the tree itself: four rules share this analysis per module, and
    the multi-pass walk is the expensive part of the whole lint run."""
    cached = getattr(tree, "_raftlint_traced", None)
    if cached is not None:
        return cached
    traced: Dict[ast.AST, Set[str]] = {}
    passed_names: Dict[str, Set[str]] = {}  # fn name -> static names/nums seen

    def statics_for(fn: ast.AST, call: Optional[ast.Call]) -> Set[str]:
        if call is None:
            return set()
        names, nums = _static_arg_spec(call)
        pos = _positional_params(fn) if isinstance(fn, _FUNCS) else []
        return names | {pos[i] for i in nums if i < len(pos)}

    for node in ast.walk(tree):
        if isinstance(node, _FUNCS):
            for deco in node.decorator_list:
                if _mentions_tracer(deco):
                    call = next((n for n in ast.walk(deco)
                                 if isinstance(n, ast.Call)), None)
                    traced[node] = traced.get(node, set()) | statics_for(node, call)
        elif isinstance(node, ast.Call) and terminal_name(node.func) in TRACERS:
            args = list(node.args) + [kw.value for kw in node.keywords]
            names, _nums = _static_arg_spec(node)
            for a in args:
                if isinstance(a, ast.Name):
                    passed_names.setdefault(a.id, set()).update(names)
                elif isinstance(a, ast.Lambda):
                    traced.setdefault(a, set())

    if passed_names:
        for node in ast.walk(tree):
            if isinstance(node, _FUNCS) and node.name in passed_names:
                # positional static_argnums can't be mapped here without
                # the call's arg order; static_argnames covers the idiom
                traced[node] = traced.get(node, set()) | passed_names[node.name]

    # lexical propagation: a def nested inside a traced def is traced
    # (it inherits the enclosing statics — closure params stay visible)
    def nest(node, inherited):
        statics = traced.get(node)
        inside = statics is not None or inherited is not None
        if inside:
            statics = (statics or set()) | (inherited or set())
            traced[node] = statics
        passed = statics if inside else None
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNCS + (ast.Lambda,)):
                nest(child, passed)
            else:
                _descend(child, passed)

    def _descend(node, inherited):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNCS + (ast.Lambda,)):
                nest(child, inherited)
            else:
                _descend(child, inherited)

    for node in tree.body if hasattr(tree, "body") else ():
        if isinstance(node, _FUNCS + (ast.Lambda,)):
            nest(node, None)
        else:
            _descend(node, None)
    tree._raftlint_traced = traced
    return traced


def _param_names(fn: ast.AST) -> Set[str]:
    if isinstance(fn, (ast.Lambda,) + _FUNCS):
        a = fn.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return set(names)
    return set()


def _body_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a traced function's own body without re-entering nested
    defs (they are traced themselves and checked separately, so each
    finding is reported exactly once, against its innermost function)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _FUNCS + (ast.Lambda,)):
            stack.extend(ast.iter_child_nodes(node))


def _mentions_lax(node: ast.AST) -> bool:
    return any(
        isinstance(n, (ast.Name, ast.Attribute))
        and (terminal_name(n) == "lax"
             or (dotted_chain(n) or ())[:1] == ("lax",)
             or "lax" in (dotted_chain(n) or ()))
        for n in ast.walk(node)
    )


@rule(
    "trace-host-effect",
    "host side effects (time.*/os.*/print/datetime.*) inside traced code",
    "raft_tpu/, bench/",
)
def check_host_effect(module: Module) -> Iterator[Finding]:
    if not _in_scope(module.path):
        return
    for fn in _collect_traced(module.tree):
        for node in _body_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted_chain(node.func)
            if chain and chain[0] in HOST_EFFECT_ROOTS and len(chain) > 1:
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "trace-host-effect",
                    f"host call {'.'.join(chain)}() inside traced code "
                    f"(fires at trace time, not run time)")
            elif isinstance(node.func, ast.Name) and node.func.id == "print":
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "trace-host-effect",
                    "print() inside traced code (fires at trace time; use "
                    "jax.debug.print for runtime prints)")


@rule(
    "trace-nondeterminism",
    "module-level RNG (random.*/np.random.*) inside traced code",
    "raft_tpu/, bench/",
)
def check_nondeterminism(module: Module) -> Iterator[Finding]:
    if not _in_scope(module.path):
        return
    for fn in _collect_traced(module.tree):
        for node in _body_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted_chain(node.func)
            if not chain:
                continue
            if chain[0] == "random" or (
                    chain[0] in ("np", "numpy") and len(chain) > 1
                    and chain[1] == "random"):
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "trace-nondeterminism",
                    f"module-level RNG {'.'.join(chain)}() inside traced "
                    f"code bakes one trace-time draw into the compiled "
                    f"program; thread a jax.random key instead")


@rule(
    "trace-host-sync",
    ".item()/.tolist()/float(arg) on traced arguments inside traced code",
    "raft_tpu/, bench/",
)
def check_host_sync(module: Module) -> Iterator[Finding]:
    if not _in_scope(module.path):
        return
    for fn, statics in _collect_traced(module.tree).items():
        # static args (static_argnames/static_argnums) are Python values
        # at trace time: float(k)/int(k) on them is fine
        params = _param_names(fn) - statics
        for node in _body_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in HOST_SYNC_METHODS
                    and not node.args and not node.keywords):
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "trace-host-sync",
                    f".{node.func.attr}() inside traced code forces a "
                    f"host sync (fails under tracing)")
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in HOST_SYNC_BUILTINS
                  and len(node.args) == 1
                  and isinstance(node.args[0], ast.Name)
                  and node.args[0].id in params):
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "trace-host-sync",
                    f"{node.func.id}({node.args[0].id}) on a traced "
                    f"argument inside traced code forces a host sync")


@rule(
    "trace-try-except",
    "try/except around lax ops inside traced code",
    "raft_tpu/, bench/",
)
def check_try_except(module: Module) -> Iterator[Finding]:
    if not _in_scope(module.path):
        return
    for fn in _collect_traced(module.tree):
        for node in _body_nodes(fn):
            if isinstance(node, ast.Try) and any(
                    _mentions_lax(stmt) for stmt in node.body):
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "trace-try-except",
                    "try/except around lax ops inside traced code: traced "
                    "ops don't raise at run time, the handler only catches "
                    "trace-time errors")
