"""Layer-purity rule: enforce the subpackage import DAG.

The survey's layer map (core → comms → neighbors/serve) is a contract:
``core`` is the foundation and imports no sibling subpackage, mid
layers only reach down, and ``serve`` is the apex that nothing else
imports. The enforced relation below is the *top-level* (module-scope)
import DAG — a function-level lazy import is the sanctioned escape
hatch for upward references that must exist (e.g. ``core.faults``
publishing obs events), because it defers the dependency to call time
and keeps import order acyclic. Two edges are banned even lazily, since
no call-time need can justify them: nothing imports ``tests``, and no
subpackage imports ``serve`` (the apex must stay removable).

``ALLOWED`` is the layer map. Adding an entry is a deliberate
architecture decision — make it here, in one reviewed line, not
implicitly in whatever module first grows the import.

Scope: raft_tpu/ (plus the tests-import ban in bench/).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from tools.raftlint.engine import Finding, Module, rule

# subpackage -> sibling subpackages it may import at module scope.
# Layer order (each set only reaches down):
#   L0 core/util/native  L1 obs  L2 distance/ops/matrix/random/label/io
#   L3 cluster/sparse/linalg/solver/stats  L4 neighbors/spectral/spatial
#   L5 comms  L6 serve / jobs (siblings at the apex: neither imports
#   the other — jobs supervises work, serve answers queries)
ALLOWED = {
    "cluster": {"core", "native", "distance", "label"},
    "comms": {"core", "cluster", "distance", "matrix", "obs", "ops"},
    "core": set(),
    "distance": {"core"},
    # digests/scrub/restore sit beside the index modules the way obs
    # does: module scope builds only on core/obs, and every index,
    # mutation, comms, or serve reference resolves lazily at call time
    # (the hooks in those layers call INTO integrity, not the reverse)
    "integrity": {"core", "obs"},
    "io": {"core", "native"},
    # the job runner supervises work ACROSS layers but only builds on
    # the durable/obs foundations at module scope; index modules resolve
    # lazily at call time, and serve/bench stay sealed (a runner that
    # imported the apex could never supervise it from outside)
    "jobs": {"core", "io", "comms", "obs"},
    "label": {"core", "native"},
    "linalg": {"core"},
    "matrix": {"core", "ops"},
    "native": set(),
    "neighbors": {"core", "native", "cluster", "distance", "matrix",
                  "obs", "ops", "random"},
    "obs": {"core"},
    "ops": {"core", "distance"},
    "random": {"core"},
    "serve": {"core", "obs", "comms", "neighbors"},
    "solver": {"core"},
    "sparse": {"core", "native", "cluster", "distance", "matrix"},
    "spatial": {"core", "neighbors"},
    "spectral": {"core", "sparse", "cluster"},
    "stats": {"core", "distance"},
    "util": set(),
}

#: importable by nobody (any level); serve additionally only from the
#: package root (raft_tpu/__init__.py lazy exports) and serve itself
SEALED = {"tests"}

#: top-level packages the LIBRARY (raft_tpu/) may never import at any
#: level: the measurement layer reads the library, never the reverse —
#: obs/perf attribution and the ledger live in raft_tpu.obs precisely
#: so `bench` stays a pure consumer (bench/ files themselves are exempt)
LIB_SEALED = {"bench"}

# Per-MODULE refinements of the subpackage map: shared-foundation
# modules that several siblings inside one subpackage build on get a
# STRICTER sibling-subpackage allowance than their package, plus a ban
# on module-scope imports of the very modules that import them (a cycle
# would otherwise appear the first time someone "just needs one
# helper"). The quantizer layer (PR 6) is the canonical case: both
# ivf_pq and ivf_rabitq import it at module scope, so it must never
# import an index module back.
MODULE_ALLOWED = {
    "raft_tpu/neighbors/quantizer.py": {"core", "cluster", "distance",
                                        "matrix", "ops"},
    # the adaptive-probing budget layer (ISSUE 12): every index engine
    # imports it (and comms/serve reach it through them), so like the
    # quantizer it gets a STRICTER foundation-only allowance — notably
    # it may never touch ops (the kernels it steers sit below the
    # dispatch layer it calls through)
    "raft_tpu/neighbors/probe_budget.py": {"core", "distance", "matrix",
                                           "obs"},
    # the live-mutation layer (ISSUE 16) orchestrates ABOVE the index
    # modules (serve and jobs call it; it calls extend/save/load on all
    # three kinds), so its module scope touches only the durable/obs
    # foundations — index modules resolve lazily at call time, exactly
    # the jobs-runner posture one layer down
    "raft_tpu/neighbors/mutation.py": {"core", "obs"},
}
#: module path -> sibling MODULES (same subpackage) it must not import
#: at module scope
MODULE_CYCLE_BAN = {
    "raft_tpu/neighbors/quantizer.py": {"ivf_pq", "ivf_rabitq", "ivf_flat"},
    "raft_tpu/neighbors/probe_budget.py": {"ivf_pq", "ivf_rabitq",
                                           "ivf_flat", "probe_invert"},
    "raft_tpu/neighbors/mutation.py": {"ivf_pq", "ivf_rabitq", "ivf_flat"},
}

# Subpackage -> sibling subpackages it may never import at ANY level,
# lazy function-level included. The lazy escape hatch exists for upward
# references with a call-time need; a kernel layer reaching back into
# the layers that dispatch it has none — `ops` is imported BY matrix
# (select_k's fused dispatch) and neighbors (every fused engine), so an
# ops -> matrix/neighbors import, even lazy, closes a dispatch cycle
# the moment someone "just needs one helper" (the quantizer lesson,
# PR 6, applied one layer down).
ANY_LEVEL_BAN = {
    "ops": {"matrix", "neighbors"},
}

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _own_subpackage(path: str) -> Optional[str]:
    parts = path.split("/")
    if parts[0] != "raft_tpu":
        return None
    if len(parts) == 2:
        return "<root>"  # raft_tpu/__init__.py and friends
    return parts[1]


def _import_targets(node: ast.AST, own_parts: List[str]) -> List[str]:
    """Sibling raft_tpu subpackages referenced by one import statement
    (absolute or relative)."""
    out: List[str] = []
    if isinstance(node, ast.Import):
        for alias in node.names:
            bits = alias.name.split(".")
            if bits[0] == "raft_tpu" and len(bits) > 1:
                out.append(bits[1])
            elif bits[0] in SEALED or bits[0] in LIB_SEALED:
                out.append(bits[0])
    elif isinstance(node, ast.ImportFrom):
        if node.level == 0:
            bits = (node.module or "").split(".")
            if bits[0] == "raft_tpu":
                if len(bits) > 1:
                    out.append(bits[1])
                else:  # from raft_tpu import X, Y
                    out.extend(a.name for a in node.names)
            elif bits[0] in SEALED or bits[0] in LIB_SEALED:
                out.append(bits[0])
        else:
            # resolve "from ..X import y" against this file's package:
            # level 1 is the containing package itself, each extra level
            # climbs one parent
            up = node.level - 1
            base = own_parts[:len(own_parts) - up] if up <= len(own_parts) else []
            bits = base + ((node.module or "").split(".") if node.module else [])
            if bits and bits[0] == "raft_tpu":
                if len(bits) > 1:
                    out.append(bits[1])
                else:
                    out.extend(a.name for a in node.names)
    return out


def _sibling_module_targets(node: ast.AST, own_parts: List[str]) -> List[str]:
    """Module names inside this file's OWN subpackage referenced by one
    import statement (absolute or relative) — the granularity the
    per-module cycle bans need."""
    out: List[str] = []
    pkg = own_parts  # e.g. ["raft_tpu", "neighbors"]
    if len(pkg) < 2:
        return out
    if isinstance(node, ast.Import):
        for alias in node.names:
            bits = alias.name.split(".")
            if len(bits) > 2 and bits[0] == pkg[0] and bits[1] == pkg[1]:
                out.append(bits[2])
    elif isinstance(node, ast.ImportFrom):
        if node.level == 0:
            bits = (node.module or "").split(".")
        else:
            up = node.level - 1
            base = pkg[:len(pkg) - up] if up <= len(pkg) else []
            bits = base + ((node.module or "").split(".") if node.module else [])
        if len(bits) >= 2 and bits[0] == pkg[0] and bits[1] == pkg[1]:
            if len(bits) > 2:
                out.append(bits[2])
            else:
                out.extend(a.name for a in node.names)
    return out


def _module_scope_imports(tree: ast.AST) -> Iterator[ast.AST]:
    """Import statements at module scope, descending through top-level
    If/Try/With (conditional imports are still import-time) but not into
    functions (the lazy-import escape hatch) or classes."""
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            yield node
        elif isinstance(node, (ast.If, ast.Try, ast.With)):
            for field in ("body", "orelse", "finalbody"):
                stack.extend(getattr(node, field, ()) or ())
            for h in getattr(node, "handlers", ()) or ():
                stack.extend(h.body)
    return


@rule(
    "layer-purity",
    "subpackage import outside the layer DAG (module-scope), or a "
    "sealed package (tests/serve) imported at any level",
    "raft_tpu/, bench/",
)
def check_layers(module: Module) -> Iterator[Finding]:
    own = _own_subpackage(module.path)
    own_parts = module.path.split("/")[:-1] or ["."]
    in_bench = module.path.startswith("bench/")
    if own is None and not in_bench:
        return

    seen: Set[Tuple[int, str]] = set()

    # any-level: sealed targets
    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        for tgt in _import_targets(node, list(own_parts)):
            if tgt in SEALED:
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "layer-purity",
                    f"import of {tgt!r} from {module.path} — nothing may "
                    f"import {tgt!r} at any level")
            elif tgt in LIB_SEALED and own is not None:
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "layer-purity",
                    f"import of {tgt!r} from library module {module.path} "
                    f"— the measurement layer reads raft_tpu, never the "
                    f"reverse (obs must not import bench)")
            elif (tgt == "serve" and own not in ("serve", "<root>", None)):
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "layer-purity",
                    f"subpackage {own!r} imports 'serve' — serve is the "
                    f"apex layer, importable only from the package root")
            elif own is not None and tgt in ANY_LEVEL_BAN.get(own, ()):
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "layer-purity",
                    f"subpackage {own!r} imports {tgt!r} — banned at any "
                    f"level (even lazily): {tgt!r} dispatches into "
                    f"{own!r}, so the reverse import closes a dispatch "
                    f"cycle (tools/raftlint/rules/layers.py ANY_LEVEL_BAN)")
            else:
                continue
            seen.add((node.lineno, tgt))

    if own is None or own == "<root>":
        return

    # per-module refinement: shared-foundation modules get a stricter
    # allowance than their subpackage, plus the intra-package cycle ban
    allowed = MODULE_ALLOWED.get(module.path, ALLOWED.get(own))
    cycle_ban = MODULE_CYCLE_BAN.get(module.path, frozenset())
    for node in _module_scope_imports(module.tree):
        for tgt in _sibling_module_targets(node, list(own_parts)):
            if tgt in cycle_ban:
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "layer-purity",
                    f"module-scope import of sibling module {tgt!r} from "
                    f"the shared foundation module {module.path} closes an "
                    f"import cycle ({tgt} imports it back); use a "
                    f"function-level lazy import")
        for tgt in _import_targets(node, list(own_parts)):
            if tgt == own or tgt in SEALED or (node.lineno, tgt) in seen:
                continue
            if allowed is None:
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "layer-purity",
                    f"subpackage {own!r} is not in the layer map "
                    f"(tools/raftlint/rules/layers.py ALLOWED) — add it "
                    f"with its allowed imports")
                return
            if tgt not in allowed and tgt in ALLOWED:
                yield Finding(
                    module.path, node.lineno, node.col_offset + 1,
                    "layer-purity",
                    f"module-scope import of sibling subpackage {tgt!r} "
                    f"from {own!r} violates the layer DAG (allowed: "
                    f"{sorted(allowed)}); use a function-level lazy "
                    f"import or update the layer map deliberately")
