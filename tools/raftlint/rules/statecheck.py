"""Statecheck rules (raftlint 4.0): cache-key completeness and the
checkpoint schema registry.

``cache-key-completeness``
    Every memoized-trace site — ``_cached_wrapper`` callers across the
    MNMG serving layer, module-level ``*_CACHE`` dict caches, serve's
    per-request ``probe_key`` contract — must put every trace-shaping
    closure input into its cache key. The engine
    (tools/raftlint/statecheck.py) computes the build closure's
    enclosing-scope reads (through sibling helpers like ``finish``) and
    proves each one reaches the key expression, directly or by a
    derivation whose every reaching assignment bottoms out in keyed
    names / process-stable statics; derivations through a tuned read
    never count (mid-process ``--apply`` flips must rebuild wrappers).
    A trace input that cannot be shown to reach the key is the PR-1
    (fault-plan fingerprint), PR-4 (derived probe count), PR-12
    (adaptive flag) bug class: a stale compiled program silently serves
    under live traffic. Fail-closed: an unanalyzable key expression or
    unresolvable build reference is itself a finding.

``ckpt-schema-registry``
    ``core/serialize.py::CKPT_SCHEMA`` is the machine-readable registry
    of every checkpoint field (per index kind: array/meta/runtime
    category, dtype class, since-version, absent-on-load behavior).
    Enforced both ways: every field a ``*_save*`` path writes must be
    registered under its kind (unregistered write = a checkpoint the
    load path cannot reason about); every registered "default" field's
    load must read it GUARDED (``arrays.get`` / ``"f" in arrays``) with
    the fallback on the mainline path (the guard's block dominates a
    return — the PR-9 commit-ordering style must-reach check); loads
    must route through the version gate (``read_ckpt`` /
    ``check_ckpt_version``) so newer-than-library checkpoints refuse
    typed; and on whole-package scans the save/load field sets stay
    symmetric (a field written but never loaded — or registered but
    never written — is schema drift). "derive" fields are consumed by
    the shared heal machinery and exempt from the per-load read checks.

``integrity-digest-registry``
    ``integrity/digest.py::DIGEST_FIELDS`` is the scrub-coverage
    registry: for every digestable index kind it names which serialized
    array fields carry a per-list or per-table CRC sidecar row.
    Enforced both ways against ``CKPT_SCHEMA`` on whole-package scans:
    every array field of a digestable kind must have a digest row (a
    new serialized table cannot silently ship outside scrub coverage),
    and every digest row must name a registered array field (a dangling
    row means the scrubber hashes state that no longer round-trips).
    The sidecar fields themselves (``list_digests``/``table_digests``)
    are exempt. Fail-closed: a missing or non-literal DIGEST_FIELDS is
    itself a finding.

Scope: raft_tpu/ (cache keys live in comms/ and serve/; checkpoint
writes in neighbors/ and comms/mnmg_ckpt.py; the digest registry in
integrity/digest.py).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from tools.raftlint.cfg import build_cfg, dominators
from tools.raftlint.engine import (
    Finding,
    Module,
    project_rule,
    terminal_name,
)
from tools.raftlint.project import project_index
from tools.raftlint.statecheck import (
    CKPT_REGISTRY_RELPATH,
    DIGEST_REGISTRY_RELPATH,
    CacheSite,
    CoverageEnv,
    _assignments_in,
    _import_bound,
    collect_cache_sites,
    collect_dict_cache_sites,
    collect_load_sites,
    collect_save_sites,
    key_expr_names,
    key_tag,
    load_ckpt_schema,
    load_digest_fields,
    module_static_names,
    trace_inputs,
    tuned_reads_inside,
)

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _in_scope(path: str) -> bool:
    return path.startswith("raft_tpu/")


# -- cache-key-completeness ---------------------------------------------


def _site_findings(site: CacheSite, index) -> Iterator[Finding]:
    module = site.module
    key = site.key
    tag = key_tag(key) or "<untagged>"
    line, col = key.lineno, key.col_offset + 1
    # a Name key: chase its single local assignment to a tuple
    if isinstance(key, ast.Name):
        assigns = _assignments_in(site.chain)
        rhss = assigns.get(key.id, [])
        if len(rhss) == 1:
            key = rhss[0]
    names = key_expr_names(key)
    if names is None:
        yield Finding(
            module.path, line, col, "cache-key-completeness",
            f"memoized trace site: cache key expression is not a tuple "
            f"literal or wrapper_key(...) call — not analyzable, and an "
            f"unprovable key is treated as incomplete (fail closed)")
        return
    if site.build is None:
        yield Finding(
            module.path, line, col, "cache-key-completeness",
            f"memoized trace site {tag!r}: build callable does not "
            f"resolve to a local def/lambda — the closure's trace inputs "
            f"cannot be checked against the key (fail closed)")
        return
    static = module_static_names(module)
    inputs = trace_inputs(site.build, site.chain, static)
    env = CoverageEnv(_assignments_in(site.chain),
                      static | _import_bound_chain(site.chain),
                      module.path, index)
    covered = env.covered_closure(names)
    for name in sorted(inputs - covered):
        yield Finding(
            module.path, line, col, "cache-key-completeness",
            f"memoized trace site {tag!r}: closure input {name!r} shapes "
            f"the traced program but cannot be shown to flow into the "
            f"cache key — a stale compiled program can serve after "
            f"{name!r} changes (add it to the key, or derive it from "
            f"keyed inputs)")
    for call in tuned_reads_inside(site.build):
        yield Finding(
            module.path, call.lineno, call.col_offset + 1,
            "cache-key-completeness",
            f"memoized trace site {tag!r}: tuned-registry read inside "
            f"the memoized build closure — the compiled program bakes "
            f"one read of mutable tuned state the key never sees; "
            f"resolve it before the build and put the result in the key")


def _import_bound_chain(chain) -> Set[str]:
    out: Set[str] = set()
    for fn in chain:
        out |= _import_bound(fn)
    return out


def _dict_cache_findings(module: Module, index) -> Iterator[Finding]:
    for site in collect_dict_cache_sites(module):
        names = key_expr_names(site.key)
        if names is None:
            continue  # non-tuple dict keys: out of this rule's model
        params: Set[str] = set()
        if isinstance(site.fn, _FUNCS):
            a = site.fn.args
            params = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
        static = module_static_names(module) | _import_bound(site.fn)
        env = CoverageEnv(_assignments_in([site.fn]), static, module.path,
                          index)
        covered = env.covered_closure(names)
        needed: Set[str] = set()
        queue = list(site.value_exprs)
        seen_names: Set[str] = set()
        while queue:
            expr = queue.pop()
            for n in ast.walk(expr):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                    if n.id in seen_names:
                        continue
                    seen_names.add(n.id)
                    needed.add(n.id)
                    queue.extend(env.assigns.get(n.id, []))
        uncovered = sorted(n for n in needed & params if n not in covered)
        for name in uncovered:
            yield Finding(
                module.path, site.key_node.lineno,
                site.key_node.col_offset + 1, "cache-key-completeness",
                f"module-level cache {site.cache_name or '<cache>'} keyed "
                f"without {name!r}: the cached value is built from "
                f"parameter {name!r} but the key tuple never sees it — "
                f"two calls differing only in {name!r} share one stale "
                f"entry")


def _probe_key_findings(module: Module) -> Iterator[Finding]:
    """Serve-layer compile-cache contract: a Searcher whose search()
    derives per-request work from probe_scale/recall_target must
    override probe_key — otherwise two requests that compile different
    programs share one (bucket, k) cache entry (the PR-4 class)."""
    if not module.path.startswith("raft_tpu/serve/"):
        return
    for node in module.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        if not any(terminal_name(b) == "Searcher" for b in node.bases):
            continue  # the contract binds Searcher subclasses only
        methods = {m.name: m for m in node.body if isinstance(m, _FUNCS)}
        search = methods.get("search")
        if search is None or "probe_key" in methods:
            continue
        sig = {p.arg for p in search.args.args + search.args.kwonlyargs}
        if not ({"probe_scale", "recall_target"} & sig):
            continue
        used = sorted(
            n.id for n in ast.walk(search)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
            and n.id in ("probe_scale", "recall_target"))
        if used:
            yield Finding(
                module.path, search.lineno, search.col_offset + 1,
                "cache-key-completeness",
                f"searcher {node.name!r}: search() derives per-request "
                f"work from {', '.join(sorted(set(used)))} but the class "
                f"inherits the exact-searcher probe_key — the serve "
                f"compile-cache key misses the probe dimension (override "
                f"probe_key with the derived token)")


@project_rule(
    "cache-key-completeness",
    "a memoized-trace site's cache key misses a trace-shaping closure "
    "input: a stale compiled program silently serves after it changes",
    "raft_tpu/ (comms wrapper caches, module *_CACHE dicts, serve "
    "probe_key contract)",
)
def check_cache_key_completeness(modules, repo_root) -> Iterator[Finding]:
    index = project_index(modules)
    for module in modules:
        if not _in_scope(module.path):
            continue
        for site in collect_cache_sites(module):
            yield from _site_findings(site, index)
        yield from _dict_cache_findings(module, index)
        yield from _probe_key_findings(module)


# -- ckpt-schema-registry -----------------------------------------------


def _guards_cover_returns(fn: ast.AST, guard_nodes: List[ast.AST],
                          every_return: bool) -> bool:
    """The PR-9 must-reach style check, load-path flavor: the guarded
    read sits on the mainline. For a single-kind load every
    value-return must be dominated by SOME guard (a branch that
    constructs and returns the index without the fallback is exactly
    the bug); multi-kind dispatchers check at least one return per
    guard set — their other returns belong to other kinds' paths and
    cannot be attributed here (under-report, never guess)."""
    cfg = build_cfg(fn)
    dom = dominators(cfg)
    gbs = [b for b in (cfg.block_of(g) for g in guard_nodes)
           if b is not None]
    if not gbs:
        return False
    covered_any = False
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            rb = cfg.block_of(node)
            if rb is None:
                continue
            hit = any(gb in dom[rb] for gb in gbs)
            covered_any = covered_any or hit
            if every_return and not hit:
                return False
    return covered_any


@project_rule(
    "ckpt-schema-registry",
    "checkpoint field sets must match core/serialize.py::CKPT_SCHEMA: "
    "unregistered save fields, missing/off-mainline load fallbacks, "
    "ungated versions, and save/load asymmetry are schema drift",
    "raft_tpu/ (neighbors/ saves+loads, comms/mnmg_ckpt.py)",
)
def check_ckpt_schema_registry(modules, repo_root) -> Iterator[Finding]:
    index = project_index(modules)
    schema, src_path = load_ckpt_schema(modules, repo_root)
    save_sites = collect_save_sites(modules, index)
    load_sites = collect_load_sites(modules, index)
    if schema is None:
        if save_sites or load_sites:
            anchor = src_path or CKPT_REGISTRY_RELPATH
            yield Finding(
                anchor, 1, 1, "ckpt-schema-registry",
                "CKPT_SCHEMA registry missing or not a literal dict in "
                f"{CKPT_REGISTRY_RELPATH} — checkpoint writes exist but "
                "cannot be checked; restore the literal dict")
        return

    written: Dict[str, Set[str]] = {}
    # save coverage: every written field registered under its kind
    for site in sorted(save_sites,
                       key=lambda s: (s.module.path, s.node.lineno)):
        for reason, anchor in site.unresolved:
            yield Finding(
                site.module.path, anchor.lineno, anchor.col_offset + 1,
                "ckpt-schema-registry",
                f"checkpoint write not analyzable ({reason}) — an "
                f"unverifiable field set fails closed; write dict-literal "
                f"fields (or a resolvable helper) so the registry check "
                f"can see them")
        if site.kind is None:
            continue
        spec = schema.get(site.kind)
        if spec is None:
            yield Finding(
                site.module.path, site.node.lineno,
                site.node.col_offset + 1, "ckpt-schema-registry",
                f"checkpoint write declares kind {site.kind!r} but "
                f"CKPT_SCHEMA has no such kind — register it with its "
                f"field schema")
            continue
        bucket = written.setdefault(site.kind, set())
        for cat, pairs in (("array", site.array_keys),
                           ("meta", site.meta_keys)):
            for name, anchor in pairs:
                bucket.add(name)
                f = spec.fields.get(name)
                if f is None:
                    yield Finding(
                        site.module.path, anchor.lineno,
                        anchor.col_offset + 1, "ckpt-schema-registry",
                        f"save path writes unregistered {site.kind} "
                        f"{cat} field {name!r} — register it in "
                        f"CKPT_SCHEMA (category, dtype class, "
                        f"since-version, absent-on-load behavior) so "
                        f"loads have a declared compat story")
                elif f.category != cat:
                    yield Finding(
                        site.module.path, anchor.lineno,
                        anchor.col_offset + 1, "ckpt-schema-registry",
                        f"{site.kind} field {name!r} is registered as "
                        f"{f.category!r} but written as {cat!r}")

    # load checks: version gate, guarded optional reads, fallbacks on
    # the mainline
    read: Dict[str, Set[str]] = {}
    for site in sorted(load_sites,
                       key=lambda s: (s.module.path, s.fn.lineno)):
        all_acc = site.accesses + site.helper_accesses
        acc_by_field: Dict[str, List] = {}
        for a in all_acc:
            acc_by_field.setdefault(a.field, []).append(a)
        own_fields = {a.field for a in site.accesses}
        for kind in site.kinds:
            spec = schema.get(kind)
            if spec is None:
                continue
            bucket = read.setdefault(kind, set())
            bucket.update(acc_by_field)
            if not site.calls_gate:
                yield Finding(
                    site.module.path, site.fn.lineno,
                    site.fn.col_offset + 1, "ckpt-schema-registry",
                    f"load path for kind {kind!r} never reaches the "
                    f"schema gate (read_ckpt / check_ckpt_version) — a "
                    f"checkpoint declaring a newer version than the "
                    f"library would load by guesswork instead of "
                    f"refusing typed")
            for name, f in sorted(spec.fields.items()):
                if name in ("kind", "version") or f.category == "runtime":
                    continue  # consumed by the core gate / never stored
                accesses = acc_by_field.get(name, [])
                if f.absent != "default":
                    continue
                guards = [a for a in site.accesses
                          if a.field == name and a.guarded]
                unguarded = [a for a in site.accesses
                             if a.field == name and not a.guarded]
                if name not in own_fields:
                    continue  # not this load's field (symmetry covers it)
                if unguarded and not guards:
                    yield Finding(
                        site.module.path, unguarded[0].node.lineno,
                        unguarded[0].node.col_offset + 1,
                        "ckpt-schema-registry",
                        f"{kind} field {name!r} is declared "
                        f"absent='default' but the load reads it "
                        f"UNGUARDED — a legacy checkpoint without it "
                        f"crashes instead of falling back (use .get / "
                        f"an `in` test)")
                elif guards and not _guards_cover_returns(
                        site.fn, [g.node for g in guards],
                        every_return=len(site.kinds) == 1):
                    yield Finding(
                        site.module.path, guards[0].node.lineno,
                        guards[0].node.col_offset + 1,
                        "ckpt-schema-registry",
                        f"{kind} field {name!r}: the legacy-load "
                        f"fallback is not on the mainline load path "
                        f"(its block dominates no return) — some loads "
                        f"construct the index without ever applying "
                        f"the declared absent='default' behavior")

    # symmetry: whole-package scans only (a subdirectory lint has no
    # basis to call a field unwritten/unread)
    scanned = {m.path for m in modules}
    if CKPT_REGISTRY_RELPATH not in scanned \
            or "raft_tpu/__init__.py" not in scanned:
        return
    for kind in sorted(schema):
        spec = schema[kind]
        wrote = written.get(kind, set())
        got = read.get(kind, set())
        for name, f in sorted(spec.fields.items()):
            if f.category == "runtime":
                continue
            if not wrote and kind not in written:
                continue  # kind has no resolvable save site at all
            if name not in wrote and f.absent != "derive" \
                    and name != "version":
                yield Finding(
                    src_path, f.line, f.col, "ckpt-schema-registry",
                    f"registered {kind} field {name!r} is never written "
                    f"by any {kind} save path — dead registry entry or "
                    f"a save that silently stopped persisting it")
            if name in ("kind", "version") or f.absent == "derive":
                continue
            if kind in read and name not in got:
                yield Finding(
                    src_path, f.line, f.col, "ckpt-schema-registry",
                    f"registered {kind} field {name!r} is written but "
                    f"never read by any {kind} load path — the state "
                    f"does not round-trip (load it, or declare it "
                    f"absent='derive' with the re-derivation)")


# -- integrity-digest-registry ------------------------------------------

#: the sidecar's own storage fields: digesting the digests only detects
#: rot a mismatch already surfaces, so the registry exempts them
_SIDECAR_FIELDS = frozenset({"list_digests", "table_digests"})


@project_rule(
    "integrity-digest-registry",
    "every CKPT_SCHEMA array field of a digestable kind must carry a "
    "digest row in integrity/digest.py::DIGEST_FIELDS (and every row "
    "must name a registered array field) — drift means tables serving "
    "outside scrub coverage",
    "raft_tpu/ (whole-package scans; core/serialize.py vs "
    "integrity/digest.py)",
)
def check_integrity_digest_registry(modules, repo_root) -> Iterator[Finding]:
    # whole-scan gated like the ckpt symmetry checks: a subdirectory
    # lint has no basis to call either registry incomplete
    scanned = {m.path for m in modules}
    if CKPT_REGISTRY_RELPATH not in scanned \
            or "raft_tpu/__init__.py" not in scanned:
        return
    schema, _schema_path = load_ckpt_schema(modules, repo_root)
    if schema is None:
        return  # ckpt-schema-registry already reports this, once
    digests, src_path = load_digest_fields(modules, repo_root)
    if digests is None:
        anchor = src_path or DIGEST_REGISTRY_RELPATH
        yield Finding(
            anchor, 1, 1, "integrity-digest-registry",
            "DIGEST_FIELDS registry missing or not a literal dict of "
            f"'list'/'table' granularities in {DIGEST_REGISTRY_RELPATH} "
            "— scrub coverage cannot be checked; restore the literal "
            "(fail closed)")
        return
    for kind in sorted(digests):
        spec = schema.get(kind)
        rows = digests[kind]
        if spec is None:
            first = min(rows.values(), key=lambda d: d.line, default=None)
            yield Finding(
                src_path, first.line if first else 1,
                first.col if first else 1, "integrity-digest-registry",
                f"DIGEST_FIELDS declares kind {kind!r} but CKPT_SCHEMA "
                f"has no such kind — the scrubber would hash state the "
                f"checkpoint layer does not know")
            continue
        for name, f in sorted(spec.fields.items()):
            if f.category != "array" or name in _SIDECAR_FIELDS:
                continue
            if name not in rows:
                yield Finding(
                    src_path, f.line, f.col, "integrity-digest-registry",
                    f"{kind} array field {name!r} has no DIGEST_FIELDS "
                    f"row — it would serve outside scrub coverage; add "
                    f"it with its granularity ('list' per-IVF-list, "
                    f"'table' whole) and teach integrity.digest.refresh "
                    f"when it moves")
        for name, d in sorted(rows.items()):
            f = spec.fields.get(name)
            if f is None or f.category != "array":
                yield Finding(
                    src_path, d.line, d.col, "integrity-digest-registry",
                    f"DIGEST_FIELDS row {kind}.{name} names "
                    + ("no registered checkpoint field" if f is None
                       else f"a {f.category!r} field")
                    + " — digest rows must track CKPT_SCHEMA array "
                    "fields (dangling rows hash state that does not "
                    "round-trip)")
