"""SPMD collective rules: rank-divergent reachability and
cross-path emission-order drift.

The MNMG layer is single-program-multiple-data over XLA collectives:
every rank must enter every collective, in the same order, or the mesh
deadlocks (the BENCH_r01–r05 hang class — hours of debugging per
incident, because a hung allgather attributes to no rank). The two rule
families here machine-check that contract on the CFG:

``collective-divergence``
    A branch (or loop) whose predicate is **rank-dependent** — derived
    from ``get_rank``/``axis_index``/``process_index``, from host
    health state (``RankHealth`` masks, ``.degraded``/``.coverage``),
    or from per-host filesystem probes (``os.path.exists`` on a
    non-shared path) — after which the two sides disagree on *which*
    collectives run. Ranks taking different sides then wait on each
    other forever. Detected via control dependence + the per-side
    emission-sequence sets, so an early ``return`` guards everything
    after it even though nothing is lexically nested under the ``if``.
    Calls into collective-emitting callees count (project summaries),
    so ``if health.degraded: repair(...)`` fires even though the
    ``ppermute`` lives two calls away.

``collective-order``
    Both sides of such a branch emit the *same* collectives but in
    **different sequences** — no rank skips a collective, yet ranks on
    different sides pair their allreduce with the other side's
    allgather. XLA cannot diagnose this; it just wedges or silently
    mixes payloads.

Branches on *uniform* predicates (static config, shapes, the same plan
object on every rank) are exempt by construction: the taint engine only
flags predicates that can genuinely differ per rank/host. Intentional
rank-asymmetric code (driver-only rank-0 work, single-controller heal
loops) carries a justified pragma on the branch line — the finding
anchors at the *decision*, not at each collective under it.

Scope: raft_tpu/ (collectives live in comms/, jobs/, serve heal paths).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from tools.raftlint.cfg import (
    CFG,
    build_cfg,
    emission_sequences,
    guard_blocks,
)
from tools.raftlint.engine import Finding, Module, project_rule
from tools.raftlint.project import (
    ProjectIndex,
    local_taints,
    project_index,
    taint_reason,
)

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _in_scope(path: str) -> bool:
    return path.startswith("raft_tpu/")


def _all_functions(module: Module) -> Iterator[Tuple[ast.AST, Optional[str]]]:
    """Every def at any nesting depth, with its enclosing class qname
    (for ``self.m()`` resolution). Nested defs are analyzed as their own
    functions — a shard_map body's branches matter as much as its
    driver's."""

    def walk(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, f"{module.path}::{child.name}")
            elif isinstance(child, _FUNCS):
                yield child, cls
                yield from walk(child, cls)
            elif not isinstance(child, ast.Lambda):
                yield from walk(child, cls)

    yield from walk(module.tree, None)


def _nested_emitters(fn: ast.AST, module: Module, index: ProjectIndex,
                     cls: Optional[str]) -> Dict[str, bool]:
    """Directly nested def names that (transitively) emit collectives:
    their *reference* inside `fn` (``shard_map(body)``, ``retry(fn=...)``)
    is the emission point the CFG sees."""
    out: Dict[str, bool] = {}
    for child in ast.walk(fn):
        if child is fn or not isinstance(child, _FUNCS):
            continue
        emits = False
        for node in ast.walk(child):
            if isinstance(node, ast.Call):
                if index.collective_token(node, module.path, cls=cls):
                    emits = True
                    break
        if emits:
            out[child.name] = True
    return out


def _stmt_tokens(stmt: ast.AST, module: Module, index: ProjectIndex,
                 cls: Optional[str], nested: Dict[str, bool]) -> List[str]:
    """Collective op tokens emitted by one statement, in source order.
    Skips nested def bodies (their emissions attribute at reference
    sites); a Name load of an emitting nested def counts as its
    emission."""
    out: List[Tuple[Tuple[int, int], str]] = []
    stack = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNCS + (ast.Lambda,)):
            continue  # the def statement itself emits nothing
        if isinstance(node, ast.Call):
            token = index.collective_token(node, module.path, cls=cls)
            if token is not None:
                out.append(((node.lineno, node.col_offset), token))
        elif (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
              and nested.get(node.id)):
            out.append(((node.lineno, node.col_offset), f"{node.id}()"))
        stack.extend(ast.iter_child_nodes(node))
    return [t for _pos, t in sorted(out)]


def _analyze(fn: ast.AST, module: Module, index: ProjectIndex,
             cls: Optional[str]):
    """(divergence findings, order findings) for one function. Cached on
    the node: both rules share one pass."""
    cached = getattr(fn, "_raftlint_coll", None)
    if cached is not None:
        return cached

    nested = _nested_emitters(fn, module, index, cls)
    cfg = build_cfg(fn)
    block_tokens: Dict[int, Tuple[str, ...]] = {}
    for bid in cfg.sorted_ids():
        blk = cfg.blocks[bid]
        toks: List[str] = []
        if blk.test is not None:
            toks += _stmt_tokens(blk.test, module, index, cls, nested)
        for stmt in blk.stmts:
            toks += _stmt_tokens(stmt, module, index, cls, nested)
        if toks:
            block_tokens[bid] = tuple(toks)

    div: List[Finding] = []
    order: List[Finding] = []
    if not block_tokens:
        fn._raftlint_coll = (div, order)
        return fn._raftlint_coll

    taints = local_taints(fn, index, module.path, cls=cls)

    def emit(blk):
        return block_tokens.get(blk.id, ())

    for bid in cfg.sorted_ids():
        blk = cfg.blocks[bid]
        if blk.test is None or len(blk.succs) < 2:
            continue
        reason = taint_reason(blk.test, taints, index, module.path, cls=cls)
        if reason is None:
            continue
        line, col = blk.test.lineno, blk.test.col_offset + 1
        if blk.kind == "loop":
            # a collective inside a loop whose trip count is
            # rank-dependent: ranks run different collective COUNTS
            inside = [b for b, toks in sorted(block_tokens.items())
                      if bid in guard_blocks(cfg, b)]
            if inside:
                ops = sorted({t for b in inside for t in block_tokens[b]})
                div.append(Finding(
                    module.path, line, col, "collective-divergence",
                    f"collective(s) {', '.join(ops)} inside a loop whose "
                    f"trip count depends on a {reason}-dependent value: "
                    f"ranks disagreeing on the iteration count deadlock "
                    f"the mesh (SPMD requires every rank to emit the "
                    f"same collective sequence)"))
            continue
        seqsets = [emission_sequences(cfg, s, emit) for s in blk.succs]
        if any(s is None for s in seqsets):
            continue  # too wide to judge — stay silent, never guess
        if all(s == seqsets[0] for s in seqsets[1:]):
            continue
        canon = [frozenset(tuple(sorted(seq)) for seq in ss)
                 for ss in seqsets]
        if all(c == canon[0] for c in canon[1:]):
            pair = _order_witness(seqsets)
            order.append(Finding(
                module.path, line, col, "collective-order",
                f"paths from this {reason}-dependent branch emit the same "
                f"collectives in different orders "
                f"({' -> '.join(pair[0])} vs {' -> '.join(pair[1])}): "
                f"ranks on different sides pair mismatched collectives "
                f"and the mesh wedges"))
        else:
            ops_sides = [{t for seq in ss for t in seq} for ss in seqsets]
            diff = set()
            for i, ops in enumerate(ops_sides):
                for j, other in enumerate(ops_sides):
                    if i != j:
                        diff |= ops - other
            ops = sorted(diff) or sorted(set().union(*ops_sides))
            div.append(Finding(
                module.path, line, col, "collective-divergence",
                f"collective(s) {', '.join(ops)} reachable on only one "
                f"side of this {reason}-dependent branch: ranks taking "
                f"the other side never enter them and the mesh deadlocks "
                f"(guard collectives with uniform predicates, or agree "
                f"the decision across ranks first)"))

    # ternary flavor: `x = coll() if rank_dep else other` — expression-
    # level control flow the CFG doesn't split. Own nodes only: nested
    # defs are analyzed as their own functions, and walking into them
    # here would report each of their ternaries twice
    own: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, _FUNCS + (ast.Lambda,)):
            continue
        own.append(n)
        stack.extend(ast.iter_child_nodes(n))
    for node in own:
        if not isinstance(node, ast.IfExp):
            continue
        reason = taint_reason(node.test, taints, index, module.path, cls=cls)
        if reason is None:
            continue
        sides = [tuple(_stmt_tokens(node.body, module, index, cls, nested)),
                 tuple(_stmt_tokens(node.orelse, module, index, cls, nested))]
        if sides[0] != sides[1] and any(sides):
            ops = sorted(set(sides[0]) ^ set(sides[1])) or sorted(
                set(sides[0]) | set(sides[1]))
            div.append(Finding(
                module.path, node.test.lineno, node.test.col_offset + 1,
                "collective-divergence",
                f"collective(s) {', '.join(ops)} on only one arm of a "
                f"{reason}-dependent conditional expression: ranks "
                f"evaluating the other arm never enter them"))

    fn._raftlint_coll = (div, order)
    return fn._raftlint_coll


def _order_witness(seqsets) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    """Two concrete differing sequences to show in the message."""
    for i, a in enumerate(seqsets):
        for b in seqsets[i + 1:]:
            only_a = sorted(a - b)
            only_b = sorted(b - a)
            if only_a and only_b:
                return only_a[0], only_b[0]
    flat = sorted({s for ss in seqsets for s in ss})
    return (flat[0], flat[-1]) if flat else ((), ())


@project_rule(
    "collective-divergence",
    "collective reachable only under a rank-/health-/filesystem-dependent "
    "predicate (directly or through callees): the SPMD deadlock class",
    "raft_tpu/",
)
def check_collective_divergence(modules, repo_root) -> Iterator[Finding]:
    index = project_index(modules)
    for module in modules:
        if not _in_scope(module.path):
            continue
        for fn, cls in _all_functions(module):
            yield from _analyze(fn, module, index, cls)[0]


@project_rule(
    "collective-order",
    "two rank-dependently-selected paths through one function emit "
    "collectives in different sequences",
    "raft_tpu/",
)
def check_collective_order(modules, repo_root) -> Iterator[Finding]:
    index = project_index(modules)
    for module in modules:
        if not _in_scope(module.path):
            continue
        for fn, cls in _all_functions(module):
            yield from _analyze(fn, module, index, cls)[1]
