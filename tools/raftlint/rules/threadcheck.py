"""Threadcheck rules (raftlint 5.0): thread-root registry drift, the
whole-program shared-state race rule, and the publication-safety rule
that machine-checks the zero-dip single-reference-swap contract.

Built on tools/raftlint/threads.py (scope table, thread-root discovery,
per-root reachability, lock-context access sets). Four rules:

  thread-root-unknown   a discovered ``Thread(target=...)`` spawn or
                        callback registration whose target is not in
                        ``THREAD_ROOTS`` — or cannot be resolved at all
                        (fail closed: an invisible thread entry is a
                        hole in every race guarantee); also fires when
                        the registry itself is missing/malformed while
                        spawn sites exist.
  thread-root-unused    a registered root no spawn/registration site
                        resolves to (stale registry entry). Whole-scan
                        gated like ``fault-site-unused``.
  shared-state-race     an attribute (or module global) reachable from
                        ≥2 thread roots with at least one write, where
                        the access sites share no common lock and the
                        writes are not all whole-reference swaps. One
                        finding per (class, attr), anchored at the
                        first racy write.
  publication-safety    the zero-dip contract: state readable from
                        another thread root must be published as a
                        single reference swap. Fires on (a) field
                        stores through a shared reference
                        (``self.index.lists = ...``) and (b) a method
                        publishing ≥2 distinct cross-root-read fields
                        by separate unguarded swaps (readers can see
                        the pair half-applied).

Benign races are suppressed with the justified-pragma convention
(``# raftlint: disable=shared-state-race  -- <why>``; docs/linting.md
has the catalog). Scope: raft_tpu/ for the race rules; raft_tpu/ and
bench/ for root discovery (bench drives the server with client threads;
tests/ spin ad-hoc threads under schedfuzz control and are excluded on
purpose).
"""

from __future__ import annotations

import collections
from typing import Dict, FrozenSet, List, Sequence, Set, Tuple

from tools.raftlint.engine import Finding, Module, project_rule
from tools.raftlint.threads import (
    CALLER_ROOT,
    REGISTRY_RELPATH,
    Access,
    ThreadIndex,
    load_registry,
    thread_index,
)

_ROOT_SCOPE = ("raft_tpu/", "bench/")
_RACE_SCOPE = ("raft_tpu/",)


def _short_root(qname: str) -> str:
    """'raft_tpu/serve/engine.py::SearchServer._run' -> 'SearchServer._run'
    (registry keys stay unique enough per class for messages)."""
    return qname.rsplit("::", 1)[-1] if "::" in qname else qname


def _roots_and_map(tidx: ThreadIndex, modules: Sequence[Module]):
    registry = load_registry(modules)
    discovered: Set[str] = set()
    for site in tidx.spawn_sites + tidx.callback_sites:
        discovered.update(site.targets)
    roots = sorted((set(registry or {}) | discovered)
                   & set(tidx.scopes))
    return registry, discovered, roots, tidx.root_map(roots)


# -- registry drift ------------------------------------------------------

@project_rule(
    "thread-root-unknown",
    "thread spawn/callback target missing from THREAD_ROOTS (or "
    "unresolvable: fail closed)",
    "raft_tpu/, bench/",
)
def thread_root_unknown(modules: Sequence[Module], repo_root: str):
    tidx = thread_index(modules)
    registry = load_registry(modules)
    sites = [s for s in tidx.spawn_sites + tidx.callback_sites
             if s.module.startswith(_ROOT_SCOPE)]
    if registry is None:
        scanned = {m.path for m in modules}
        if sites and REGISTRY_RELPATH in scanned:
            # present but unparseable as a literal dict: fail closed
            yield Finding(
                REGISTRY_RELPATH, 1, 1, "thread-root-unknown",
                "THREAD_ROOTS must be a module-level dict literal of "
                "str -> str (threadcheck reads it by AST)")
        elif sites:
            s = min(sites, key=lambda x: (x.module, x.line, x.col))
            yield Finding(
                s.module, s.line, s.col, "thread-root-unknown",
                f"thread entry points exist but {REGISTRY_RELPATH} is "
                "not in the scan set: the THREAD_ROOTS contract cannot "
                "be checked (fail closed)")
        return
    for s in sorted(sites, key=lambda x: (x.module, x.line, x.col)):
        if not s.targets:
            yield Finding(
                s.module, s.line, s.col, "thread-root-unknown",
                f"unresolvable {s.detail} target: threadcheck cannot "
                "attribute this execution context to a root — use a "
                "named def/method (or a justified pragma)")
            continue
        for t in s.targets:
            if t not in registry:
                yield Finding(
                    s.module, s.line, s.col, "thread-root-unknown",
                    f"thread root '{t}' ({s.detail}) is not registered "
                    f"in THREAD_ROOTS ({REGISTRY_RELPATH})")


@project_rule(
    "thread-root-unused",
    "THREAD_ROOTS entry no spawn/registration site resolves to "
    "(stale registry)",
    "raft_tpu/, bench/ (whole-package scans only)",
)
def thread_root_unused(modules: Sequence[Module], repo_root: str):
    scanned = {m.path for m in modules}
    # only a whole-package scan can call a root dead (same gate as
    # fault-site-unused): spawn sites spread across serve/jobs/obs/bench
    if REGISTRY_RELPATH not in scanned or \
            "raft_tpu/__init__.py" not in scanned:
        return
    registry = load_registry(modules)
    if registry is None:
        return  # thread-root-unknown already failed closed
    tidx = thread_index(modules)
    discovered: Set[str] = set()
    for site in tidx.spawn_sites + tidx.callback_sites:
        discovered.update(site.targets)
    reg_mod = next(m for m in modules if m.path == REGISTRY_RELPATH)
    lines = {}
    for i, text in enumerate(reg_mod.lines, 1):
        for key in registry:
            if f'"{key}"' in text or f"'{key}'" in text:
                lines.setdefault(key, i)
    for key in sorted(registry):
        if key.startswith("bench/") and not any(
                p.startswith("bench/") for p in scanned):
            continue  # bench/ not in this scan: no basis to call it dead
        if key not in discovered:
            yield Finding(
                REGISTRY_RELPATH, lines.get(key, 1), 1,
                "thread-root-unused",
                f"registered thread root '{key}' matches no discovered "
                "spawn/registration site (stale entry, or the target "
                "moved)")


# -- race analysis -------------------------------------------------------

def _owner_groups(tidx: ThreadIndex):
    groups: Dict[Tuple[str, str, str], List[Access]] = \
        collections.defaultdict(list)
    for a in tidx.accesses:
        if not a.module.startswith(_RACE_SCOPE):
            continue
        if a.owner[0] == "attr" and a.scope == a.owner[1] + ".__init__":
            continue  # construction happens-before every share
        groups[a.owner].append(a)
    return groups


def _owner_label(owner: Tuple[str, str, str]) -> str:
    kind, where, name = owner
    if kind == "attr":
        return f"{where.rsplit('::', 1)[-1]}.{name}"
    return f"{where}::{name} (module global)"


def _roots_of(accs: List[Access],
              rmap: Dict[str, FrozenSet[str]]) -> Set[str]:
    out: Set[str] = set()
    for a in accs:
        out |= rmap.get(a.scope, frozenset({CALLER_ROOT}))
    return out


def _common_locks(accs: List[Access]) -> FrozenSet:
    """Locks held at EVERY write site. Write-side mutual exclusion is
    the proof obligation; a lock-free read of a consistently-locked
    structure only reads the attribute reference (atomic under the
    GIL), and the residual read-tear class — a reader observing a
    locked writer's intermediate states — is a documented
    under-report (the alternative flags every ``self._get(self._tbl)``
    reference pass-through in the repo)."""
    common = None
    for a in accs:
        if a.kind not in ("write", "write_through"):
            continue
        common = a.locks if common is None else (common & a.locks)
    return common if common is not None else frozenset()


@project_rule(
    "shared-state-race",
    "attr/global reachable from >=2 thread roots, written without a "
    "common lock (whole-reference swaps exempt)",
    "raft_tpu/",
)
def shared_state_race(modules: Sequence[Module], repo_root: str):
    tidx = thread_index(modules)
    _, _, roots, rmap = _roots_and_map(tidx, modules)
    if not roots:
        return
    groups = _owner_groups(tidx)
    for owner in sorted(groups):
        accs = groups[owner]
        writes = [a for a in accs if a.kind in ("write", "write_through")]
        if not writes:
            continue
        shared_roots = _roots_of(accs, rmap)
        if len(shared_roots) < 2:
            continue
        if all(a.swap for a in writes):
            continue  # pure reference publication: old-or-new, never torn
        if _common_locks(accs):
            continue
        non_swap = sorted((a for a in writes if not a.swap),
                          key=lambda a: (a.module, a.line, a.col))
        if all(a.kind == "write_through" for a in non_swap):
            continue  # publication-safety owns the field-store pattern
        anchor = non_swap[0]
        rs = "+".join(sorted(_short_root(r) for r in shared_roots))
        yield Finding(
            anchor.module, anchor.line, anchor.col, "shared-state-race",
            f"'{_owner_label(owner)}' is shared across thread roots "
            f"({rs}) with a non-atomic write and no common lock over "
            f"its {len(accs)} access sites; guard every access with "
            "one lock or publish via a single reference swap")


@project_rule(
    "publication-safety",
    "zero-dip contract: cross-thread-visible state must publish as a "
    "single reference swap",
    "raft_tpu/",
)
def publication_safety(modules: Sequence[Module], repo_root: str):
    tidx = thread_index(modules)
    _, _, roots, rmap = _roots_and_map(tidx, modules)
    if not roots:
        return
    groups = _owner_groups(tidx)
    # (a) field stores through a shared reference: self.a.f = v mutates
    # the object other roots are reading through self.a
    for owner in sorted(groups):
        accs = groups[owner]
        wt = sorted((a for a in accs if a.kind == "write_through"),
                    key=lambda a: (a.module, a.line, a.col))
        if not wt:
            continue
        if len(_roots_of(accs, rmap)) < 2 or _common_locks(accs):
            continue
        seen_scopes: Set[str] = set()
        for a in wt:
            if a.scope in seen_scopes:
                continue
            seen_scopes.add(a.scope)
            yield Finding(
                a.module, a.line, a.col, "publication-safety",
                f"field-by-field mutation of shared "
                f"'{_owner_label(owner)}': another thread root can "
                "observe the object half-updated — build a fresh "
                "object and publish it with one reference swap")
    # (b) one method publishing >=2 distinct cross-root-read fields by
    # separate unguarded swaps: each swap is atomic, the PAIR is not
    by_scope: Dict[str, List[Tuple[Tuple, Access]]] = \
        collections.defaultdict(list)
    for owner in sorted(groups):
        accs = groups[owner]
        if len(_roots_of(accs, rmap)) < 2 or _common_locks(accs):
            continue
        for a in accs:
            if a.kind == "write" and a.swap and not a.locks:
                by_scope[a.scope].append((owner, a))
    for scope in sorted(by_scope):
        pairs = by_scope[scope]
        owners = sorted({o for o, _ in pairs})
        if len(owners) < 2:
            continue
        anchor = min((a for _, a in pairs),
                     key=lambda a: (a.line, a.col))
        names = ", ".join(_owner_label(o) for o in owners)
        yield Finding(
            anchor.module, anchor.line, anchor.col, "publication-safety",
            f"'{scope.rsplit('::', 1)[-1]}' publishes {len(owners)} "
            f"cross-thread-visible fields ({names}) by separate swaps: "
            "readers can observe the set half-applied — combine them "
            "into one object published by a single reference swap")
