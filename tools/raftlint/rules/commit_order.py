"""Commit-ordering rule: cursors are written LAST.

The jobs subsystem's crash-atomicity protocol (PR 8, docs/jobs.md) is
two writes in a fixed order: first the artifact (index checkpoint,
dataset chunk, manifest-named output) through a durable writer
(``atomic_write`` / an index's CRC'd ``save`` / ``fsync``), then the
small cursor/marker/manifest sidecar that *points at it*. A kill
between the two leaves the cursor at the previous (intact) artifact and
the resume is bit-identical. Written the other way round, a kill leaves
a cursor naming bytes that were never committed — the resume
double-ingests a batch or reads a torn file, silently.

This rule machine-checks the order on the CFG: inside any function that
performs both kinds of write, every cursor-class write (a
``write_json``-family call whose target names a cursor/marker/manifest/
progress file) must be **must-reach covered** by artifact-class
writes — on *every* path entry→cursor, an artifact write already
happened. A single artifact write that dominates the cursor (the common
shape) satisfies this; so does one artifact write per branch arm before
the join. Flow (not lexical order) is the right primitive: an artifact
write inside only ONE branch does not protect a cursor write after the
join, however many lines above it sits. Computed as a forward
must-analysis over the CFG (available-expressions style: a block is
covered iff it writes an artifact or ALL its predecessors are covered);
mid-block exceptional exits are approximated at block granularity.

Functions with no artifact write are skipped (pure sidecar helpers like
``JobDir.write_json`` itself); pairing cursor to artifact across
function boundaries is out of scope — keep the two writes of one commit
protocol in one function, which is also what makes the protocol
reviewable.

Scope: raft_tpu/ and bench/ (job scripts write cursors too).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Tuple

from tools.raftlint.cfg import CFG, build_cfg
from tools.raftlint.engine import Finding, Module, rule, terminal_name

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

#: writer call names whose first argument names the cursor-class file
CURSOR_WRITERS = {"write_json"}

#: what makes a write target "cursor-class"
CURSOR_NAME_RE = re.compile(r"cursor|marker|manifest|progress", re.I)

#: artifact-class (durable payload) writers: the atomic container
#: writer, an index/checkpoint save, or an fsync'd in-place grow
ARTIFACT_TERMINALS = {"atomic_write", "fsync", "write_array_header_1_0"}


def _is_artifact_write(call: ast.Call) -> bool:
    name = terminal_name(call.func)
    if name is None:
        return False
    return name in ARTIFACT_TERMINALS or name.endswith("save") \
        or name.endswith("save_local")


def _is_cursor_write(call: ast.Call) -> Optional[str]:
    """The cursor-ish identifier that classifies this call, or None."""
    if terminal_name(call.func) not in CURSOR_WRITERS or not call.args:
        return None
    target = call.args[0]
    for node in ast.walk(target):
        for text in (
            node.id if isinstance(node, ast.Name) else None,
            node.attr if isinstance(node, ast.Attribute) else None,
            node.value if isinstance(node, ast.Constant)
            and isinstance(node.value, str) else None,
        ):
            if text and CURSOR_NAME_RE.search(text):
                return text
    return None


def _own_calls(fn: ast.AST) -> Iterator[ast.Call]:
    """Calls in this function's own body, nested defs excluded (they
    are checked as their own functions)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNCS + (ast.Lambda,)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _all_functions(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, _FUNCS):
            yield node


def _covered_blocks(cfg: CFG, artifact_blocks) -> dict:
    """Forward must-analysis: block id -> True iff EVERY path from the
    entry to the block's END passes an artifact write. Greatest-fixpoint
    init (all True except the entry) so loop back-edges don't spuriously
    clear coverage established before the loop."""
    covered = {b: True for b in cfg.blocks}
    covered[cfg.entry] = cfg.entry in artifact_blocks
    changed = True
    while changed:
        changed = False
        for b in cfg.sorted_ids():
            if b == cfg.entry:
                continue
            preds = cfg.blocks[b].preds
            new = b in artifact_blocks or (
                bool(preds) and all(covered[p] for p in preds))
            if new != covered[b]:
                covered[b] = new
                changed = True
    return covered


@rule(
    "commit-ordering",
    "cursor/marker/manifest write not dominated by the artifact write it "
    "publishes (cursor-written-LAST atomicity)",
    "raft_tpu/, bench/",
)
def check_commit_ordering(module: Module) -> Iterator[Finding]:
    if not module.path.startswith(("raft_tpu/", "bench/")):
        return
    for fn in _all_functions(module.tree):
        artifacts: List[ast.Call] = []
        cursors: List[Tuple[ast.Call, str]] = []
        for call in _own_calls(fn):
            label = _is_cursor_write(call)
            if label is not None:
                cursors.append((call, label))
            elif _is_artifact_write(call):
                artifacts.append(call)
        if not cursors or not artifacts:
            # pure sidecar helpers (JobDir.write_json itself) and pure
            # artifact writers have no intra-function protocol to check
            continue
        cfg = build_cfg(fn)
        art_blocks = {cfg.block_of(a) for a in artifacts} - {None}
        covered = _covered_blocks(cfg, art_blocks)
        for call, label in cursors:
            cb = cfg.block_of(call)
            # protected iff an artifact write precedes it in its own
            # block, or every predecessor path is already covered
            ok = cb is not None and (
                any(cfg.block_of(a) == cb
                    and (a.lineno, a.col_offset) < (call.lineno,
                                                    call.col_offset)
                    for a in artifacts)
                or (bool(cfg.blocks[cb].preds)
                    and all(covered[p] for p in cfg.blocks[cb].preds)))
            if not ok:
                yield Finding(
                    module.path, call.lineno, call.col_offset + 1,
                    "commit-ordering",
                    f"cursor-class write ({label!r}) is reachable without "
                    f"an artifact write on some path: a crash here leaves "
                    f"the cursor pointing at bytes that were never "
                    f"committed — write the artifact (atomic_write / save "
                    f"/ fsync) first on every path (cursor-written-LAST)")
