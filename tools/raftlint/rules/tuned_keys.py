"""tuned-key-registry: the measured-dispatch keys and the
``core.tuned.TUNED_KEYS`` registry must agree, all three ways.

The measure->flip loop (bench --apply writes ``tuned_defaults.json``,
"auto" dispatch reads it) fails SILENTLY on a typo: an unregistered
read key means the dispatch consults a value no bench will ever write
(permanent heuristic fallback), a registered-but-never-read key is a
bench measuring a knob nothing consults, and an --apply writer spelling
a key wrong banks a chip session's winner where no reader will find it
— the queue slot is burnt and the flip never happens. The FAULT_SITES
pattern applies: ``TUNED_KEYS`` is a machine-readable literal dict
(``key -> {"kind", "choices", "bench"}``) read by AST, never by import,
and this rule enforces:

  - every ``tuned.get``/``tuned.get_choice`` key literal (or ``*_KEY``
    constant resolving to one) is registered;
  - every module-level ``<NAME>_KEY = "literal"`` constant in raft_tpu/
    names a registered key (the dedupe contract: ad-hoc key constants
    must come from the registry's spelling);
  - every registered key is read somewhere (whole-package scans only);
  - every ``tuned.merge`` writer writes only registered keys, and for
    ``kind: "choice"`` keys only literal values in the allowed set
    (computed values are unverifiable and stay silent — documented);
  - ``hints`` (kind ``"hints"``) is read only through the
    ``tuned.hints()`` helper, so the null-vs-missing tuned-file
    semantics cannot diverge between engines again.

Scope: raft_tpu/ and bench/ (benches are the writers; tests exercise
synthetic keys on temp tuned files and are exempt).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.raftlint.engine import (
    Finding,
    Module,
    const_str,
    dotted_chain,
    load_module,
    project_rule,
    terminal_name,
)

REGISTRY_RELPATH = "raft_tpu/core/tuned.py"
KEY_CONST_RE = re.compile(r"^[A-Z0-9_]*_KEY$")

_READ_FUNCS = {"get", "get_choice"}


def _in_scope(path: str) -> bool:
    return path.startswith(("raft_tpu/", "bench/"))


def _is_tuned_receiver(func: ast.AST) -> bool:
    """``tuned.get`` / ``_tuned.get_choice`` / ``core.tuned.get`` — the
    receiver chain must end in a component named ``tuned``."""
    chain = dotted_chain(func)
    return (chain is not None and len(chain) >= 2
            and chain[-2].lstrip("_") == "tuned")


def load_registry(modules, repo_root) -> Tuple[Dict[str, dict], Optional[str]]:
    """TUNED_KEYS entries with their source positions, read from the
    scanned set or from disk (AST only — raft_tpu is never imported)."""
    reg_mod = next((m for m in modules if m.path == REGISTRY_RELPATH), None)
    if reg_mod is None:
        abspath = os.path.join(repo_root, REGISTRY_RELPATH)
        if os.path.exists(abspath):
            reg_mod, _err = load_module(abspath, repo_root)
    if reg_mod is None:
        return {}, None
    for node in ast.walk(reg_mod.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "TUNED_KEYS"
                for t in node.targets):
            if not isinstance(node.value, ast.Dict):
                return {}, reg_mod.path
            out: Dict[str, dict] = {}
            for key, val in zip(node.value.keys, node.value.values):
                k = const_str(key)
                if k is None or not isinstance(val, ast.Dict):
                    continue
                entry = {"pos": (key.lineno, key.col_offset + 1),
                         "kind": None, "choices": None, "bench": None}
                for fk, fv in zip(val.keys, val.values):
                    fname = const_str(fk)
                    if fname == "kind":
                        entry["kind"] = const_str(fv)
                    elif fname == "choices":
                        if isinstance(fv, (ast.Tuple, ast.List)):
                            entry["choices"] = tuple(
                                e.value for e in fv.elts
                                if isinstance(e, ast.Constant))
                    elif fname == "bench":
                        entry["bench"] = const_str(fv)
                out[k] = entry
            return out, reg_mod.path
    return {}, reg_mod.path


# -- constant resolution --------------------------------------------------


def _module_consts(module: Module) -> Dict[str, str]:
    out = {}
    for node in module.tree.body:
        if isinstance(node, ast.Assign) and const_str(node.value) is not None:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = const_str(node.value)
    return out


class _ConstTable:
    """Project-wide string constants + per-module import maps, for
    resolving ``tuned.get(POLICY_KEY)`` through a constant defined in
    another module (``from raft_tpu.core.tuned import POLICY_KEY`` or
    ``probe_budget.POLICY_KEY``)."""

    def __init__(self, modules, repo_root):
        self.by_module: Dict[str, Dict[str, str]] = {}
        self.imports: Dict[str, Dict[str, Tuple]] = {}
        self.repo_root = repo_root
        self._extra: Dict[str, Dict[str, str]] = {}
        for m in modules:
            self.by_module[m.path] = _module_consts(m)
            imports: Dict[str, Tuple] = {}
            pkg = m.path.rsplit("/", 1)[0].split("/")
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        imports[a.asname or a.name.split(".")[0]] = (
                            "module", a.name)
                elif isinstance(node, ast.ImportFrom):
                    base = node.module or ""
                    if node.level:
                        up = pkg[: len(pkg) - (node.level - 1)]
                        base = ".".join(up + ([base] if base else []))
                    for a in node.names:
                        if a.name != "*":
                            imports[a.asname or a.name] = (
                                "symbol", base, a.name)
            self.imports[m.path] = imports

    def _consts_of(self, relpath: str) -> Dict[str, str]:
        if relpath in self.by_module:
            return self.by_module[relpath]
        if relpath not in self._extra:
            abspath = os.path.join(self.repo_root, relpath)
            consts: Dict[str, str] = {}
            if os.path.exists(abspath):
                mod, _err = load_module(abspath, self.repo_root)
                if mod is not None:
                    consts = _module_consts(mod)
            self._extra[relpath] = consts
        return self._extra[relpath]

    def resolve(self, module_path: str, node: ast.AST) -> Optional[str]:
        """The string a key expression denotes, or None."""
        s = const_str(node)
        if s is not None:
            return s
        imports = self.imports.get(module_path, {})
        if isinstance(node, ast.Name):
            local = self.by_module.get(module_path, {}).get(node.id)
            if local is not None:
                return local
            imp = imports.get(node.id)
            if imp is not None and imp[0] == "symbol":
                return self._consts_of(
                    imp[1].replace(".", "/") + ".py").get(imp[2])
            return None
        if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                          ast.Name):
            imp = imports.get(node.value.id)
            if imp is not None:
                dotted = imp[1] if imp[0] == "module" \
                    else f"{imp[1]}.{imp[2]}"
                return self._consts_of(
                    dotted.replace(".", "/") + ".py").get(node.attr)
        return None


# -- read/write collection ------------------------------------------------


def _iter_reads(module: Module) -> Iterator[Tuple[ast.Call, ast.AST, str]]:
    """(call, key expr, func name) for tuned.get/get_choice calls."""
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr in _READ_FUNCS \
                and _is_tuned_receiver(node.func) and node.args:
            yield node, node.args[0], node.func.attr


def _enclosing_functions(module: Module) -> List[ast.AST]:
    out = []
    for node in module.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(node)
        elif isinstance(node, ast.ClassDef):
            out.extend(x for x in node.body
                       if isinstance(x, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)))
    return out


def _written_keys(fn: ast.AST, merge_arg: ast.AST, consts: _ConstTable,
                  module_path: str) -> List[Tuple[str, Optional[ast.AST],
                                                  int, int]]:
    """Literal keys (with value nodes) flowing into a tuned.merge arg:
    dict literals, ``name[key] = v`` subscript stores, ``dict(base,
    kw=v)`` and ``{**base, ...}`` merges — one bounded name-chase.
    Dynamic keys are unverifiable and stay silent (documented)."""
    out: List[Tuple[str, Optional[ast.AST], int, int]] = []
    seen_names: Set[str] = set()

    def from_dict(d: ast.Dict):
        for k, v in zip(d.keys, d.values):
            if k is None:  # {**spread}
                if isinstance(v, ast.Name):
                    chase(v.id)
                elif isinstance(v, ast.Dict):
                    from_dict(v)
                continue
            key = consts.resolve(module_path, k)
            if key is not None:
                out.append((key, v, k.lineno, k.col_offset + 1))

    def from_expr(e: ast.AST):
        if isinstance(e, ast.Dict):
            from_dict(e)
        elif isinstance(e, ast.Name):
            chase(e.id)
        elif isinstance(e, ast.Call) and terminal_name(e.func) == "dict":
            for a in e.args:
                from_expr(a)
            for kw in e.keywords:
                if kw.arg is not None:
                    out.append((kw.arg, kw.value, kw.value.lineno,
                                kw.value.col_offset + 1))
                elif isinstance(kw.value, (ast.Name, ast.Dict)):
                    from_expr(kw.value)

    def chase(name: str):
        if name in seen_names:
            return
        seen_names.add(name)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        from_expr(node.value)
                    elif isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == name:
                        key = consts.resolve(module_path, t.slice)
                        if key is not None:
                            out.append((key, node.value, t.slice.lineno,
                                        t.slice.col_offset + 1))

    from_expr(merge_arg)
    return out


@project_rule(
    "tuned-key-registry",
    "tuned.get/get_choice keys, *_KEY constants, and bench --apply "
    "writers must agree with core.tuned.TUNED_KEYS (registered, read "
    "somewhere, allowed values); hints reads go through tuned.hints()",
    "raft_tpu/, bench/",
)
def check_tuned_key_registry(modules, repo_root) -> Iterator[Finding]:
    registry, src_path = load_registry(modules, repo_root)
    consts = _ConstTable([m for m in modules if _in_scope(m.path)],
                         repo_root)
    scope = [m for m in modules if _in_scope(m.path)]

    reads: List[Tuple[str, str, int, int, str, str]] = []
    hints_reads = False
    for m in scope:
        for call, key_expr, fname in _iter_reads(m):
            key = consts.resolve(m.path, key_expr)
            if key is not None:
                reads.append((key, m.path, key_expr.lineno,
                              key_expr.col_offset + 1, fname,
                              "read"))
        # `tuned.hints()` IS the sanctioned read of the "hints" key
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "hints" \
                    and _is_tuned_receiver(node.func):
                hints_reads = True

    if not registry:
        # fail CLOSED, like the fault-site registry: reads exist but the
        # registry is gone or not a literal dict
        if reads:
            anchor = src_path or reads[0][1]
            yield Finding(
                anchor, 1, 1, "tuned-key-registry",
                f"TUNED_KEYS registry missing or not a literal dict in "
                f"{REGISTRY_RELPATH} — tuned keys exist but cannot be "
                f"checked; restore the literal dict")
        return

    used: Set[str] = set()
    if hints_reads:
        used.add("hints")
    # -- reads
    for key, path, line, col, fname, _k in reads:
        used.add(key)
        if path == REGISTRY_RELPATH:
            continue  # the registry module's own helpers
        entry = registry.get(key)
        if entry is None:
            yield Finding(
                path, line, col, "tuned-key-registry",
                f"tuned key {key!r} (via tuned.{fname}) is not in "
                f"core.tuned.TUNED_KEYS — register it or fix the "
                f"spelling (an unregistered key silently falls back to "
                f"the heuristic default forever)")
        elif entry["kind"] == "hints":
            yield Finding(
                path, line, col, "tuned-key-registry",
                f"read {key!r} through tuned.hints(), not "
                f"tuned.{fname}: the helper is what keeps null-vs-"
                f"missing semantics identical across engines")

    # -- *_KEY constants in raft_tpu/ must spell registered keys
    for m in scope:
        if not m.path.startswith("raft_tpu/") or m.path == REGISTRY_RELPATH:
            continue
        for node in m.tree.body:
            if not (isinstance(node, ast.Assign)
                    and const_str(node.value) is not None):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name) and KEY_CONST_RE.match(t.id):
                    key = const_str(node.value)
                    used.add(key)
                    if key not in registry:
                        yield Finding(
                            m.path, node.value.lineno,
                            node.value.col_offset + 1,
                            "tuned-key-registry",
                            f"key constant {t.id} = {key!r} is not in "
                            f"core.tuned.TUNED_KEYS — register it or fix "
                            f"the spelling")

    # -- writers: tuned.merge call sites
    for m in scope:
        if m.path == REGISTRY_RELPATH:
            continue
        for fn in _enclosing_functions(m):
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "merge"
                        and _is_tuned_receiver(node.func) and node.args):
                    continue
                for key, val, line, col in _written_keys(
                        fn, node.args[0], consts, m.path):
                    used.add(key)
                    entry = registry.get(key)
                    if entry is None:
                        yield Finding(
                            m.path, line, col, "tuned-key-registry",
                            f"--apply writes unregistered tuned key "
                            f"{key!r}: no dispatch path reads it, so the "
                            f"measured winner is banked where nothing "
                            f"will ever find it")
                        continue
                    if entry["kind"] == "choice" and entry["choices"] \
                            and isinstance(val, ast.Constant) \
                            and val.value not in entry["choices"]:
                        yield Finding(
                            m.path, val.lineno, val.col_offset + 1,
                            "tuned-key-registry",
                            f"--apply writes {val.value!r} to {key!r}, "
                            f"not one of its registered choices "
                            f"{tuple(entry['choices'])} — readers will "
                            f"reject it and fall back")

    # -- unused registry entries (whole-package scans only, like the
    # fault-site rule: a subdirectory lint has no basis to call a key
    # dead)
    scanned = {m.path for m in modules}
    if REGISTRY_RELPATH in scanned and "raft_tpu/__init__.py" in scanned \
            and src_path is not None:
        for key in sorted(registry):
            if key not in used:
                line, col = registry[key]["pos"]
                yield Finding(
                    src_path, line, col, "tuned-key-registry",
                    f"registered tuned key {key!r} is never read by any "
                    f"dispatch path or written by any bench — dead "
                    f"registry entry")
