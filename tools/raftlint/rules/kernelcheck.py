"""kernelcheck rules: the Pallas kernel/envelope/dispatch contracts,
machine-checked (raftlint 3.0; analysis core in tools/raftlint/kernels).

Why lint-time: every fused-kernel defect in this family surfaces ON
CHIP — a VMEM envelope that under-charges its kernel OOMs the first
real grid step, one that over-charges silently refuses workloads that
fit (the dispatch falls back and the queue slot measures the wrong
engine), a drifted index_map arity or operand dtype dies in Mosaic
compile, and an unguarded fused call site violates the PR-10/11
"explicit past-envelope requests raise" contract only when a too-large
index finally arrives. Chip sessions are the scarce resource (ROADMAP
item 1); these rules burn none of them.

``kernel-vmem-envelope``
    For every kernel registered in the module's ``KERNEL_ENVELOPES``
    pairing (the FAULT_SITES pattern: ``{"fused_topk": ("fits_fused",
    {binding overrides}), ...}``), the per-grid-step VMEM bytes the
    kernel actually allocates (in/out blocks, symbolic over the shared
    parameter names; revisited buffers once; scalar-prefetch operands
    are SMEM and uncharged) are compared monomial-by-monomial against
    the AST-evaluated envelope formula. Envelope coefficient below the
    kernel's on any monomial = under-charge (chip OOM). An envelope
    total exceeding 2x the kernel's blocks+intermediates at concrete
    probe geometries = over-charge (refused workloads that fit).
    Registered kernels the interpreter cannot analyze fail CLOSED.

``kernel-blockspec-consistency``
    Structural geometry checks on EVERY ``pl.pallas_call`` site in
    raft_tpu/: index_map arity == grid rank + num_scalar_prefetch
    (checked per optional-operand variant — the PR-12 ``chunk_valid``
    second prefetch operand is exactly where ``*s`` arity drifts),
    index_map result rank == block rank, out block rank == out_shape
    rank, operand count == in_spec count, and the out_shape dtype ==
    the dtype the kernel body finally stores.

``kernel-dtype-flow``
    Abstract dtype propagation through registered kernels' bodies: MXU
    ``dot``/``dot_general`` operands must be (bf16, bf16) -> f32 or
    (int8, int8) -> int32 (an f32 operand reaching the MXU runs at
    half rate silently — TPU-KNN's peak-FLOP/s claim is exactly about
    not doing that), and ``population_count`` operands must be
    unsigned. Unregistered kernels are exempt: the full-precision f32
    kernels (pairwise_pallas, fused_l2_argmin) are f32 by design.

``dispatch-envelope-guard``
    Every call site routing into the fused kernel family (the ops
    entry points and the ``matrix/select_k`` list/bitplane dispatch
    doors) must be guarded by the matching ``fits_*`` /
    ``check_*_request`` validation on every path: a lexically
    dominating guard, a branch on a strategy variable whose every
    reaching assignment is either a non-fused literal, a resolver that
    validates, or a fused literal assigned under a guard — or, for
    private impls, the same proof at every project call site.
    Intentional exceptions carry a justified pragma on the call line.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.raftlint.engine import (
    Finding,
    Module,
    project_rule,
    rule,
    terminal_name,
)
from tools.raftlint.kernels import (
    BlockSpecV,
    CannotEval,
    KernelSite,
    Poly,
    SDSV,
    analyze_module,
    envelope_info,
    probe_eval,
    PROBE_POINTS,
)

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

#: over-charge tolerance: the envelope may conservatively pad, but
#: charging more than every block AND every intermediate the body can
#: hold, twice over, refuses workloads that fit
OVERCHARGE_FACTOR = 2.0
OVERCHARGE_SLACK = 65536


def _in_scope(path: str) -> bool:
    return path.startswith("raft_tpu/")


def _registry_line(module: Module) -> Tuple[int, int]:
    for node in module.tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "KERNEL_ENVELOPES"
                for t in node.targets):
            return node.lineno, node.col_offset + 1
    return 1, 1


# -- kernel-vmem-envelope -------------------------------------------------


@rule(
    "kernel-vmem-envelope",
    "a registered Pallas kernel's per-grid-step block bytes and its "
    "fits_* envelope formula disagree (under-charge = chip OOM, "
    "over-charge = refused workloads that fit)",
    "raft_tpu/ modules declaring KERNEL_ENVELOPES",
)
def check_vmem_envelope(module: Module) -> Iterator[Finding]:
    if not _in_scope(module.path):
        return
    ana = analyze_module(module)
    if ana.registry is None:
        return
    reg_line, reg_col = _registry_line(module)
    interp = ana.interp
    seen_msgs: Set[str] = set()

    def emit(line, col, msg):
        if msg not in seen_msgs:
            seen_msgs.add(msg)
            yield Finding(module.path, line, col, "kernel-vmem-envelope", msg)

    # coverage: every pallas wrapper in a registered module must be
    # paired (a new kernel without an envelope is unguardable)
    for wrapper in ana.pallas_wrappers:
        if wrapper not in ana.registry:
            fn = interp.functions[wrapper]
            yield from emit(
                fn.lineno, fn.col_offset + 1,
                f"kernel {wrapper!r} contains a pallas_call but is not "
                f"paired with an envelope in KERNEL_ENVELOPES")

    for wrapper, (env_name, bindings) in sorted(ana.registry.items()):
        wfn = interp.functions.get(wrapper)
        if wfn is None:
            yield from emit(
                reg_line, reg_col,
                f"KERNEL_ENVELOPES pairs {wrapper!r} but no such function "
                f"exists in this module")
            continue
        efn = interp.functions.get(env_name)
        if efn is None:
            yield from emit(
                reg_line, reg_col,
                f"KERNEL_ENVELOPES pairs {wrapper!r} with {env_name!r} but "
                f"no such envelope function exists in this module")
            continue
        einfo = envelope_info(interp, efn, bindings)
        if einfo.bytes_poly is None:
            yield from emit(
                efn.lineno, efn.col_offset + 1,
                f"envelope {env_name!r} is not symbolically evaluable "
                f"({einfo.failed}) — the cross-check fails closed")
            continue
        sites = ana.sites.get(wrapper) or []
        if not sites:
            yield from emit(
                wfn.lineno, wfn.col_offset + 1,
                f"registered kernel {wrapper!r}: no analyzable pallas_call "
                f"site found — the cross-check fails closed")
            continue
        for site in sites:
            if site.body is not None and site.body.failed:
                # fail CLOSED: an unanalyzable body means the dtype-flow
                # and final-store checks saw nothing — the registry
                # entry must not turn the gate green unverified
                yield from emit(
                    wfn.lineno, wfn.col_offset + 1,
                    f"registered kernel {wrapper!r} [{site.variant}]: "
                    f"kernel body not analyzable ({site.body.failed}) — "
                    f"the cross-check fails closed")
                continue
            blocks, why = site.block_bytes()
            if why is not None:
                yield from emit(
                    wfn.lineno, wfn.col_offset + 1,
                    f"registered kernel {wrapper!r} [{site.variant}]: "
                    f"{why} — the cross-check fails closed")
                continue
            # under-charge: the envelope must cover every block term
            for mono, need, got in blocks.monomials_below(einfo.bytes_poly):
                yield from emit(
                    efn.lineno, efn.col_offset + 1,
                    f"envelope {env_name!r} under-charges kernel "
                    f"{wrapper!r} [{site.variant}]: block bytes term "
                    f"{mono} needs coefficient >= {need}, formula has "
                    f"{got} — a fitting verdict can VMEM-OOM on chip")
            # over-charge: probe-point totals
            inters = site.body.intermediates if site.body else Poly.const(0)
            for point in PROBE_POINTS:
                try:
                    ev = probe_eval(interp, einfo.bytes_poly, point,
                                    dict(_itemsize_probe(bindings)))
                    bv = probe_eval(interp, blocks, point,
                                    dict(_itemsize_probe(bindings)))
                    iv = probe_eval(interp, inters, point,
                                    dict(_itemsize_probe(bindings)))
                except (CannotEval, ZeroDivisionError, OverflowError):
                    continue
                bound = OVERCHARGE_FACTOR * (bv + iv) + OVERCHARGE_SLACK
                if ev > bound:
                    yield from emit(
                        efn.lineno, efn.col_offset + 1,
                        f"envelope {env_name!r} over-charges kernel "
                        f"{wrapper!r} [{site.variant}]: at a probe "
                        f"geometry it charges {int(ev)} bytes vs "
                        f"{int(bv + iv)} the kernel can allocate — the "
                        f"dispatch refuses workloads that fit")
                    break


def _itemsize_probe(bindings) -> Dict[str, int]:
    out = {}
    for k, v in bindings.items():
        if k.endswith("_itemsize") and isinstance(v, int):
            out[k[:-len("_itemsize")]] = v
    return out


# -- kernel-blockspec-consistency -----------------------------------------


@rule(
    "kernel-blockspec-consistency",
    "pallas_call BlockSpec geometry drift: index_map arity vs grid rank "
    "+ scalar prefetch, index_map/block/out_shape rank, operand count, "
    "out dtype vs the kernel body's final store",
    "raft_tpu/",
)
def check_blockspec_consistency(module: Module) -> Iterator[Finding]:
    if not _in_scope(module.path):
        return
    ana = analyze_module(module)
    seen: Set[Tuple] = set()
    for wrapper in sorted(ana.sites):
        for site in ana.sites[wrapper]:
            for f in _site_consistency(module, wrapper, site):
                key = (f.line, f.col, f.message)
                if key not in seen:
                    seen.add(key)
                    yield f


def _site_consistency(module: Module, wrapper: str,
                      site: KernelSite) -> Iterator[Finding]:
    grid_rank = len(site.grid) if site.grid is not None else None
    if grid_rank is not None:
        required = grid_rank + site.nsp
        specs = list(site.in_specs) + list(site.out_specs)
        for spec in specs:
            if not isinstance(spec, BlockSpecV) or spec.index_map is None:
                continue
            lam = spec.index_map.node
            if not isinstance(lam, ast.Lambda):
                continue
            npos = len(lam.args.posonlyargs) + len(lam.args.args)
            ndef = len(lam.args.defaults)
            has_var = lam.args.vararg is not None
            ok = (npos - ndef <= required and (required <= npos or has_var))
            if not ok:
                accepts = (f">= {npos - ndef}" if has_var
                           else f"{npos - ndef}..{npos}")
                yield Finding(
                    module.path, lam.lineno, lam.col_offset + 1,
                    "kernel-blockspec-consistency",
                    f"{wrapper} [{site.variant}]: index_map takes "
                    f"{accepts} args but the grid rank ({grid_rank}) + "
                    f"num_scalar_prefetch ({site.nsp}) calls it with "
                    f"{required} — Mosaic rejects this at compile time")
            if spec.shape is not None and isinstance(lam.body, ast.Tuple) \
                    and len(lam.body.elts) != len(spec.shape):
                yield Finding(
                    module.path, lam.lineno, lam.col_offset + 1,
                    "kernel-blockspec-consistency",
                    f"{wrapper} [{site.variant}]: index_map returns "
                    f"{len(lam.body.elts)} coordinates for a rank-"
                    f"{len(spec.shape)} block")
    if site.out_specs and site.out_shapes \
            and len(site.out_specs) != len(site.out_shapes):
        yield Finding(
            module.path, site.call_node.lineno,
            site.call_node.col_offset + 1, "kernel-blockspec-consistency",
            f"{wrapper} [{site.variant}]: {len(site.out_specs)} out_specs "
            f"vs {len(site.out_shapes)} out_shape entries")
    for i, (spec, osh) in enumerate(zip(site.out_specs, site.out_shapes)):
        if isinstance(spec, BlockSpecV) and spec.shape is not None \
                and isinstance(osh, SDSV) and osh.shape is not None \
                and len(spec.shape) != len(osh.shape):
            yield Finding(
                module.path, spec.node.lineno, spec.node.col_offset + 1,
                "kernel-blockspec-consistency",
                f"{wrapper} [{site.variant}]: out block {i} has rank "
                f"{len(spec.shape)} but out_shape[{i}] has rank "
                f"{len(osh.shape)}")
    if site.in_specs and site.operands \
            and len(site.operands) != len(site.in_specs) \
            and site.scalar_count is not None:
        yield Finding(
            module.path, site.node.lineno, site.node.col_offset + 1,
            "kernel-blockspec-consistency",
            f"{wrapper} [{site.variant}]: {len(site.operands)} array "
            f"operands passed for {len(site.in_specs)} in_specs")
    if site.body is not None:
        for i, osh in enumerate(site.out_shapes):
            if not isinstance(osh, SDSV) or osh.dtype is None:
                continue
            stored = site.body.out_store_dtype(site, i)
            if stored is not None and stored != osh.dtype:
                yield Finding(
                    module.path, site.call_node.lineno,
                    site.call_node.col_offset + 1,
                    "kernel-blockspec-consistency",
                    f"{wrapper} [{site.variant}]: out_shape[{i}] declares "
                    f"{osh.dtype} but the kernel body finally stores "
                    f"{stored}")


# -- kernel-dtype-flow ----------------------------------------------------

_MXU_OK = {("bfloat16", "bfloat16"): "float32", ("int8", "int8"): "int32"}


@rule(
    "kernel-dtype-flow",
    "registered fused kernels must score (bf16,bf16)->f32 or "
    "(int8,int8)->int32 on the MXU and popcount unsigned words — an f32 "
    "operand reaching a dot runs at silent half rate",
    "raft_tpu/ modules declaring KERNEL_ENVELOPES",
)
def check_dtype_flow(module: Module) -> Iterator[Finding]:
    if not _in_scope(module.path):
        return
    ana = analyze_module(module)
    if ana.registry is None:
        return
    seen: Set[Tuple] = set()
    for wrapper in sorted(ana.registry):
        for site in ana.sites.get(wrapper) or []:
            if site.body is None:
                continue
            for d in site.body.dots:
                if d.lhs is None or d.rhs is None:
                    continue
                pref = _MXU_OK.get((d.lhs, d.rhs))
                if pref is None:
                    msg = (f"{wrapper} [{site.variant}]: MXU dot scores "
                           f"({d.lhs}, {d.rhs}) operands — fused kernels "
                           f"must score (bfloat16, bfloat16)->float32 or "
                           f"(int8, int8)->int32; an implicit upcast also "
                           f"inflates real VMEM past the envelope's charge")
                elif d.preferred is not None and d.preferred != pref:
                    msg = (f"{wrapper} [{site.variant}]: ({d.lhs}, {d.rhs}) "
                           f"dot must accumulate to {pref}, not "
                           f"{d.preferred}")
                else:
                    continue
                key = (d.node.lineno, d.node.col_offset, msg)
                if key not in seen:
                    seen.add(key)
                    yield Finding(module.path, d.node.lineno,
                                  d.node.col_offset + 1,
                                  "kernel-dtype-flow", msg)
            for p in site.body.popcounts:
                if p.dtype is not None and not p.dtype.startswith("uint"):
                    msg = (f"{wrapper} [{site.variant}]: population_count "
                           f"over {p.dtype} — bit-plane scans popcount "
                           f"uint32 words")
                    key = (p.node.lineno, p.node.col_offset, msg)
                    if key not in seen:
                        seen.add(key)
                        yield Finding(module.path, p.node.lineno,
                                      p.node.col_offset + 1,
                                      "kernel-dtype-flow", msg)


# -- dispatch-envelope-guard ----------------------------------------------

#: direct entry points into the fused kernel family: the ops kernels and
#: the matrix/select_k dispatch doors
ROUTING_FUNCS = {"fused_topk", "fused_list_topk", "fused_list_topk_int8",
                 "fused_bitplane_topk", "list_scan_select_k",
                 "bitplane_scan_select_k"}

#: envelope validations (direct names; transitive callers found by
#: summary fixpoint over the project call graph)
CHECK_FUNCS = {"fits_fused", "fits_fused_list", "fits_fused_bitplane",
               "check_fused_list_request", "check_bitplane_request"}

#: strategy literals that name a fused engine in a dispatch branch
FUSED_LITERALS = {"fused", "fused_int8", "fused_bitplane"}


def _guard_scope(path: str) -> bool:
    # ops/ is the kernel layer itself; matrix/neighbors/comms are where
    # routing decisions live
    return path.startswith("raft_tpu/") and not path.startswith(
        "raft_tpu/ops/")


Cond = Tuple[str, str]  # (ast.dump of the test, "then"|"else")


class _FnFacts:
    """Lexical facts of one top-level function: routing calls, envelope
    tokens, and name assignments — each with its branch-condition set."""

    def __init__(self):
        self.routing: List[Tuple[ast.Call, frozenset, int]] = []
        self.tokens: List[Tuple[frozenset, int]] = []
        self.assigns: Dict[str, List[Tuple[ast.AST, frozenset, int]]] = {}
        self.refs: Dict[str, List[Tuple[frozenset, int]]] = {}
        self.cond_nodes: Dict[str, ast.AST] = {}


def _collect(fn: ast.AST, module_path: str, is_check) -> _FnFacts:
    facts = _FnFacts()

    def walk(node, conds):
        if isinstance(node, ast.If):
            walk(node.test, conds)
            key = (ast.dump(node.test), "then")
            facts.cond_nodes[key[0]] = node.test
            for s in node.body:
                walk(s, conds | {key})
            for s in node.orelse:
                walk(s, conds | {(ast.dump(node.test), "else")})
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    facts.assigns.setdefault(t.id, []).append(
                        (node.value, conds, node.lineno))
                elif isinstance(t, ast.Tuple) \
                        and isinstance(node.value, ast.Tuple) \
                        and len(t.elts) == len(node.value.elts):
                    # `fused_kb, strat = None, "xla"` — pairwise
                    for te, ve in zip(t.elts, node.value.elts):
                        if isinstance(te, ast.Name):
                            facts.assigns.setdefault(te.id, []).append(
                                (ve, conds, node.lineno))
            walk(node.value, conds)
            return
        if isinstance(node, ast.Call):
            name = terminal_name(node.func)
            if name in CHECK_FUNCS or is_check(node, module_path):
                facts.tokens.append((conds, node.lineno))
            if name in ROUTING_FUNCS:
                facts.routing.append((node, conds, node.lineno))
            elif name is not None:
                facts.refs.setdefault(name, []).append((conds, node.lineno))
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            facts.refs.setdefault(node.id, []).append(
                (conds, node.lineno))
        for child in ast.iter_child_nodes(node):
            walk(child, conds)

    for stmt in (fn.body if isinstance(fn, _FUNCS) else [fn]):
        walk(stmt, frozenset())
    return facts


def _token_covers(facts: _FnFacts, conds: frozenset, line: int) -> bool:
    """A token whose conditions all hold wherever `conds` hold, emitted
    no later in the source — the check-then-route idiom."""
    return any(tc <= conds and tl <= line for tc, tl in facts.tokens)


def _strategy_guarded(facts: _FnFacts, conds: frozenset, is_check,
                      module_path: str) -> bool:
    """A branch on `<name> == "<fused literal>"` (or `in (...)`) guards
    the call when every reaching assignment of <name> is benign: a
    non-fused literal, a resolver that validates the envelope, or a
    fused literal assigned under a token."""
    for dump, pol in conds:
        if pol != "then":
            continue
        test = facts.cond_nodes.get(dump)
        name = _strategy_test_name(facts, test)
        if name is None:
            continue
        assigns = facts.assigns.get(name)
        if not assigns:
            continue
        if all(_assign_ok(facts, v, c, ln, is_check, module_path)
               for v, c, ln in assigns):
            return True
    return False


def _strategy_test_name(facts: _FnFacts, test,
                        depth: int = 0) -> Optional[str]:
    """The strategy variable a branch tests: ``strat == "fused_..."``
    directly, or (one level) a boolean flag whose every assignment is
    such a comparison (``use_fused = strat == "fused_bitplane"``)."""
    if isinstance(test, ast.Name) and depth == 0:
        inner = {
            _strategy_test_name(facts, v, 1)
            for v, _c, _l in facts.assigns.get(test.id, ())
        }
        if len(inner) == 1 and None not in inner:
            return inner.pop()
        return None
    if not isinstance(test, ast.Compare) or len(test.ops) != 1 \
            or not isinstance(test.left, ast.Name):
        return None
    if not isinstance(test.ops[0], (ast.Eq, ast.In)):
        return None
    cmp = test.comparators[0]
    lits = set()
    if isinstance(cmp, ast.Constant) and isinstance(cmp.value, str):
        lits.add(cmp.value)
    elif isinstance(cmp, (ast.Tuple, ast.List, ast.Set)):
        for e in cmp.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                lits.add(e.value)
    return test.left.id if lits & FUSED_LITERALS else None


def _assign_ok(facts, value, conds, line, is_check, module_path) -> bool:
    if isinstance(value, ast.Constant):
        if isinstance(value.value, str) and value.value in FUSED_LITERALS:
            return _token_covers(facts, conds, line)
        return True  # a non-fused literal can't select the fused branch
    if isinstance(value, ast.Call):
        name = terminal_name(value.func)
        if name in CHECK_FUNCS or is_check(value, module_path):
            return True
    return False


@project_rule(
    "dispatch-envelope-guard",
    "a call routing into the fused kernel family is not covered by the "
    "matching fits_*/check_* envelope validation on every path",
    "raft_tpu/ (matrix dispatch, neighbors/, comms/mnmg_*)",
)
def check_dispatch_envelope_guard(modules, repo_root) -> Iterator[Finding]:
    from tools.raftlint.project import project_index

    index = project_index(modules)

    # summary fixpoint: which project functions transitively reach an
    # envelope check
    has_check: Set[str] = set()
    direct: Dict[str, Set[str]] = {}
    for q, info in index.functions.items():
        callees: Set[str] = set()
        hit = False
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call):
                if terminal_name(node.func) in CHECK_FUNCS:
                    hit = True
                callees.update(index.resolve_call(info.module, node.func,
                                                  cls=info.cls))
        direct[q] = callees
        if hit:
            has_check.add(q)
    for _ in range(10):
        grew = False
        for q, callees in direct.items():
            if q not in has_check and callees & has_check:
                has_check.add(q)
                grew = True
        if not grew:
            break

    def is_check(call: ast.Call, module_path: str) -> bool:
        return any(q in has_check
                   for q in index.resolve_call(module_path, call.func))

    # per-function lexical facts, lazily
    facts_cache: Dict[int, _FnFacts] = {}

    def facts_of(fn: ast.AST, module_path: str) -> _FnFacts:
        f = facts_cache.get(id(fn))
        if f is None:
            f = _collect(fn, module_path, is_check)
            facts_cache[id(fn)] = f
        return f

    scope_mods = [m for m in modules if _guard_scope(m.path)]
    # top-level functions per module (methods included)
    fns_by_mod: Dict[str, List[ast.AST]] = {}
    for m in scope_mods:
        fns = []
        for node in m.tree.body:
            if isinstance(node, _FUNCS):
                fns.append(node)
            elif isinstance(node, ast.ClassDef):
                fns.extend(x for x in node.body if isinstance(x, _FUNCS))
        fns_by_mod[m.path] = fns

    def fn_guarded(fn: ast.AST, module_path: str, conds: frozenset,
                   line: int, depth: int, seen: Set[str]) -> bool:
        facts = facts_of(fn, module_path)
        if _token_covers(facts, conds, line):
            return True
        if _strategy_guarded(facts, conds, is_check, module_path):
            return True
        # propagate to the callers of a private impl: every reference
        # site must itself be guarded
        if not fn.name.startswith("_") or depth >= 3:
            return False
        qname = f"{module_path}::{fn.name}"
        if qname in seen:
            return False
        seen = seen | {qname}
        sites: List[Tuple[ast.AST, str, frozenset, int]] = []
        for m in scope_mods:
            for outer in fns_by_mod[m.path]:
                of = facts_of(outer, m.path)
                for conds2, line2 in of.refs.get(fn.name, ()):
                    # the name must actually resolve to this function
                    # from that module (same module or a followed import)
                    if m.path != module_path and not _imports_symbol(
                            index, m.path, fn.name, qname):
                        continue
                    sites.append((outer, m.path, conds2, line2))
        if not sites:
            return True  # no visible callers: silence, never a guess
        return all(fn_guarded(outer, mp, c2, l2, depth + 1, seen)
                   for outer, mp, c2, l2 in sites)

    for m in scope_mods:
        for fn in fns_by_mod[m.path]:
            if fn.name in ROUTING_FUNCS:
                continue  # the dispatch door itself: callers carry it
            facts = facts_of(fn, m.path)
            for call, conds, line in facts.routing:
                if not fn_guarded(fn, m.path, conds, line, 0, set()):
                    name = terminal_name(call.func)
                    yield Finding(
                        m.path, call.lineno, call.col_offset + 1,
                        "dispatch-envelope-guard",
                        f"call to {name} is not covered by its "
                        f"fits_*/check_* envelope validation on every "
                        f"path — explicit past-envelope requests must "
                        f"raise (PR-10/11 contract); add the guard or a "
                        f"justified pragma")


def _imports_symbol(index, module_path: str, name: str, qname: str) -> bool:
    imp = index.imports.get(module_path, {}).get(name)
    if imp is None or imp[0] != "symbol":
        return False
    return f"{imp[1].replace('.', '/')}.py::{imp[2]}" == qname
