"""Per-function control-flow graphs with dominance — the raftlint 2.0
analysis core.

PR 5's rules were syntactic: they could see *that* a collective call
exists, not *under which conditions it executes*. The SPMD bug classes
this engine exists for are flow-sensitive by nature — a collective
reachable only when ``rank == 0``, two branches committing collectives
in different orders, a cursor written on a path where its artifact save
was skipped. So every rule in the new families works on a `CFG`:

  - basic blocks of statements in execution order, with edges for
    branches (``if``/``while``/``for``), loop back-edges,
    ``try``/``except``/``finally`` (every block in a try body gets an
    exceptional edge to each handler; ``finally`` is on every exit
    path), and ``with`` (an exceptional ``__enter__``-failure edge from
    the entry block — ``__exit__`` runs and the exception propagates);
  - **dominance** (``a`` dominates ``b`` iff every path entry→``b``
    passes through ``a``) — the commit-ordering rule's primitive: the
    artifact write must dominate the cursor write;
  - **postdominance** and **control dependence** (Ferrante-Ottenstein-
    Warren) — the divergence rule's primitive: the branch conditions a
    collective's execution actually depends on, not just the ``if``s it
    happens to be indented under (an early ``return`` guards everything
    after it without enclosing it lexically);
  - bounded **emission-sequence enumeration** over the back-edge-cut
    DAG — the order-drift rule's primitive: the set of collective
    sequences reachable from each side of a branch.

Everything here is stdlib ``ast`` only and deterministic: block ids are
allocation-ordered, every iteration walks sorted ids, so findings built
on top sort stably.

Deliberate approximations (bounded analysis, documented over clever):
expression-level short-circuit flow (``and``/``or``, ternaries) does
not split blocks; ``assert`` and arbitrary expressions are assumed
non-raising outside ``try`` bodies; a ``finally`` block is lowered once
with edges to both its normal continuation and the function exit rather
than duplicated per exit kind.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, FrozenSet, List, Optional, Set, Tuple

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass
class Block:
    """One basic block. ``stmts`` are the AST statements lowered into it
    in execution order; ``test`` is set on branch/loop-header blocks (the
    ``if``/``while`` condition, or the ``for`` iterable) and is what the
    divergence rule taints."""

    id: int
    kind: str  # entry | exit | body | branch | loop | finally
    stmts: List[ast.AST] = dataclasses.field(default_factory=list)
    succs: List[int] = dataclasses.field(default_factory=list)
    preds: List[int] = dataclasses.field(default_factory=list)
    test: Optional[ast.AST] = None


class CFG:
    """Control-flow graph of one function (or lambda)."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.blocks: Dict[int, Block] = {}
        self._next = 0
        self._node_block: Dict[int, int] = {}  # id(ast node) -> block id
        self.entry = self._new("entry").id
        self.exit = self._new("exit").id

    # -- construction ----------------------------------------------------
    def _new(self, kind: str) -> Block:
        b = Block(self._next, kind)
        self.blocks[self._next] = b
        self._next += 1
        return b

    def _edge(self, src: int, dst: int) -> None:
        if dst not in self.blocks[src].succs:
            self.blocks[src].succs.append(dst)
            self.blocks[dst].preds.append(src)

    def _map_node(self, node: ast.AST, block_id: int) -> None:
        """Map `node` and its sub-expressions to `block_id`, without
        descending into nested function bodies (those own their own
        CFGs; only the def/lambda node itself belongs to this block)."""
        stack = [node]
        while stack:
            n = stack.pop()
            self._node_block.setdefault(id(n), block_id)
            if not isinstance(n, _FUNCS + (ast.Lambda,)):
                stack.extend(ast.iter_child_nodes(n))

    # -- queries -----------------------------------------------------------
    def block_of(self, node: ast.AST) -> Optional[int]:
        """The block a statement or sub-expression was lowered into."""
        return self._node_block.get(id(node))

    def sorted_ids(self) -> List[int]:
        return sorted(self.blocks)


class _Builder:
    def __init__(self, fn: ast.AST):
        self.cfg = CFG(fn)
        # (header_block, after_block) per enclosing loop, for continue/break
        self.loops: List[Tuple[int, int]] = []
        # innermost-first exceptional targets: handler entries of the
        # enclosing try, or the function exit
        self.exc: List[List[int]] = []
        # innermost-first finally entries return/raise/break must route via
        self.finallies: List[int] = []

    def build(self) -> CFG:
        cfg = self.cfg
        fn = cfg.fn
        start = cfg._new("body")
        cfg._edge(cfg.entry, start.id)
        if isinstance(fn, ast.Lambda):
            cfg._map_node(fn.body, start.id)
            start.stmts.append(fn.body)
            cfg._edge(start.id, cfg.exit)
            return cfg
        end = self._stmts(fn.body, start.id)
        cfg._edge(end, cfg.exit)
        return cfg

    # -- helpers -----------------------------------------------------------
    def _exc_targets(self) -> List[int]:
        return self.exc[-1] if self.exc else [self.cfg.exit]

    def _jump_out(self, cur: int, target: int) -> int:
        """Terminate `cur` with a jump to `target`, routed through the
        innermost enclosing ``finally`` when one is active. Returns a
        fresh unreachable block so lowering can continue."""
        if self.finallies:
            self.cfg._edge(cur, self.finallies[-1])
        else:
            self.cfg._edge(cur, target)
        return self.cfg._new("body").id

    def _append(self, cur: int, stmt: ast.AST) -> None:
        self.cfg.blocks[cur].stmts.append(stmt)
        self.cfg._map_node(stmt, cur)

    # -- statement lowering -------------------------------------------------
    def _stmts(self, body: List[ast.stmt], cur: int) -> int:
        for stmt in body:
            cur = self._stmt(stmt, cur)
        return cur

    def _stmt(self, node: ast.stmt, cur: int) -> int:
        cfg = self.cfg
        if isinstance(node, ast.If):
            branch = cfg.blocks[cur]
            branch.kind = "branch"
            branch.test = node.test
            cfg._map_node(node.test, cur)
            join = cfg._new("body").id
            then = cfg._new("body").id
            cfg._edge(cur, then)
            cfg._edge(self._stmts(node.body, then), join)
            if node.orelse:
                other = cfg._new("body").id
                cfg._edge(cur, other)
                cfg._edge(self._stmts(node.orelse, other), join)
            else:
                cfg._edge(cur, join)
            return join

        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            header = cfg._new("loop")
            header.test = node.test if isinstance(node, ast.While) else node.iter
            cfg._map_node(header.test, header.id)
            if isinstance(node, (ast.For, ast.AsyncFor)):
                cfg._map_node(node.target, header.id)
            cfg._edge(cur, header.id)
            after = cfg._new("body").id
            body = cfg._new("body").id
            cfg._edge(header.id, body)
            infinite = (isinstance(node, ast.While)
                        and isinstance(node.test, ast.Constant)
                        and bool(node.test.value))
            self.loops.append((header.id, after))
            body_end = self._stmts(node.body, body)
            self.loops.pop()
            cfg._edge(body_end, header.id)  # back-edge
            if node.orelse:
                orelse = cfg._new("body").id
                if not infinite:
                    cfg._edge(header.id, orelse)
                cfg._edge(self._stmts(node.orelse, orelse), after)
            elif not infinite:
                cfg._edge(header.id, after)
            return after

        if isinstance(node, ast.Try):
            return self._try(node, cur)

        if isinstance(node, (ast.With, ast.AsyncWith)):
            entry = cfg.blocks[cur]
            entry.kind = entry.kind if entry.kind != "body" else "with"
            for item in node.items:
                self._append(cur, item.context_expr)
                if item.optional_vars is not None:
                    cfg._map_node(item.optional_vars, cur)
            # __enter__ may raise: the with-exit edge — __exit__ runs and
            # the exception propagates to the handler/exit, never to the
            # statements after the with
            for t in self._exc_targets():
                cfg._edge(cur, t)
            body = cfg._new("body").id
            cfg._edge(cur, body)
            after = cfg._new("body").id
            cfg._edge(self._stmts(node.body, body), after)
            return after

        if isinstance(node, ast.Return):
            self._append(cur, node)
            return self._jump_out(cur, cfg.exit)
        if isinstance(node, ast.Raise):
            self._append(cur, node)
            if self.exc:
                for t in self._exc_targets():
                    cfg._edge(cur, t)
                return cfg._new("body").id
            return self._jump_out(cur, cfg.exit)
        if isinstance(node, ast.Break):
            self._append(cur, node)
            return self._jump_out(
                cur, self.loops[-1][1] if self.loops else cfg.exit)
        if isinstance(node, ast.Continue):
            self._append(cur, node)
            return self._jump_out(
                cur, self.loops[-1][0] if self.loops else cfg.exit)

        # plain statement (incl. nested def/class: the statement itself
        # belongs here; its body is its own CFG)
        self._append(cur, node)
        return cur

    def _try(self, node: ast.Try, cur: int) -> int:
        cfg = self.cfg
        after = cfg._new("body").id
        fin_entry: Optional[int] = None
        if node.finalbody:
            fin_entry = cfg._new("finally").id
            self.finallies.append(fin_entry)

        handler_entries: List[int] = []
        for _h in node.handlers:
            handler_entries.append(cfg._new("body").id)

        # lower the body with exceptional edges to every handler (or,
        # with no handlers, to the finally / outer targets)
        body_entry = cfg._new("body").id
        cfg._edge(cur, body_entry)
        watermark = cfg._next
        exc_to = handler_entries or ([fin_entry] if fin_entry is not None
                                     else self._exc_targets())
        self.exc.append(exc_to)
        body_end = self._stmts(node.body, body_entry)
        self.exc.pop()
        for bid in [body_entry] + list(range(watermark, cfg._next)):
            if bid in cfg.blocks and cfg.blocks[bid].kind != "finally":
                for t in exc_to:
                    cfg._edge(bid, t)

        normal_end = body_end
        if node.orelse:
            orelse_entry = cfg._new("body").id
            cfg._edge(body_end, orelse_entry)
            normal_end = self._stmts(node.orelse, orelse_entry)

        ends = [normal_end]
        for h, entry in zip(node.handlers, handler_entries):
            if h.type is not None:
                cfg._map_node(h.type, entry)
            ends.append(self._stmts(h.body, entry))

        if fin_entry is not None:
            self.finallies.pop()
            for e in ends:
                cfg._edge(e, fin_entry)
            fin_end = self._stmts(node.finalbody, fin_entry)
            cfg._edge(fin_end, after)
            # the finally also sits on exceptional/early-exit paths: it
            # can continue to the exit (or the enclosing handler) too
            for t in self._exc_targets():
                cfg._edge(fin_end, t)
        else:
            for e in ends:
                cfg._edge(e, after)
        return after


def build_cfg(fn: ast.AST) -> CFG:
    """Build the CFG of one ``FunctionDef``/``AsyncFunctionDef``/
    ``Lambda``. Memoized on the node (several rules share the graph)."""
    cached = getattr(fn, "_raftlint_cfg", None)
    if cached is None:
        cached = _Builder(fn).build()
        fn._raftlint_cfg = cached
    return cached


# -- dominance ------------------------------------------------------------

def _reachable(cfg: CFG, root: int, reverse: bool = False) -> Set[int]:
    seen = {root}
    stack = [root]
    while stack:
        b = stack.pop()
        nxt = cfg.blocks[b].preds if reverse else cfg.blocks[b].succs
        for s in nxt:
            if s not in seen:
                seen.add(s)
                stack.append(s)
    return seen


def _dom_sets(cfg: CFG, root: int, reverse: bool) -> Dict[int, FrozenSet[int]]:
    """Iterative dominator (or, with reverse=True, postdominator) sets:
    dom(b) = {b} ∪ ⋂ dom(pred(b)). Blocks unreachable from the root are
    assigned the full set (vacuously dominated — they execute never)."""
    reach = _reachable(cfg, root, reverse=reverse)
    universe = frozenset(cfg.blocks)
    dom: Dict[int, Set[int]] = {b: set(universe) for b in cfg.blocks}
    dom[root] = {root}
    order = [b for b in cfg.sorted_ids() if b in reach and b != root]
    changed = True
    while changed:
        changed = False
        for b in order:
            edges = cfg.blocks[b].succs if reverse else cfg.blocks[b].preds
            preds = [p for p in edges if p in reach]
            new = set(universe)
            for p in preds:
                new &= dom[p]
            new |= {b}
            if not preds:
                new = {b}
            if new != dom[b]:
                dom[b] = new
                changed = True
    return {b: frozenset(s) for b, s in dom.items()}


def dominators(cfg: CFG) -> Dict[int, FrozenSet[int]]:
    """block id -> the set of blocks that dominate it (itself included)."""
    cached = getattr(cfg, "_dom", None)
    if cached is None:
        cached = _dom_sets(cfg, cfg.entry, reverse=False)
        cfg._dom = cached
    return cached


def postdominators(cfg: CFG) -> Dict[int, FrozenSet[int]]:
    """block id -> the set of blocks that postdominate it."""
    cached = getattr(cfg, "_pdom", None)
    if cached is None:
        cached = _dom_sets(cfg, cfg.exit, reverse=True)
        cfg._pdom = cached
    return cached


def dominates(cfg: CFG, a: int, b: int) -> bool:
    return a in dominators(cfg)[b]


def control_deps(cfg: CFG) -> Dict[int, FrozenSet[int]]:
    """block -> branch blocks it is DIRECTLY control-dependent on
    (Ferrante-Ottenstein-Warren over the postdominator sets): B depends
    on C iff some successor path of C always reaches B while C itself
    can avoid B."""
    cached = getattr(cfg, "_cd", None)
    if cached is not None:
        return cached
    pdom = postdominators(cfg)
    cd: Dict[int, Set[int]] = {b: set() for b in cfg.blocks}
    for c in cfg.sorted_ids():
        succs = cfg.blocks[c].succs
        if len(succs) < 2:
            continue
        for s in succs:
            for b in pdom[s]:
                if b != c and b not in pdom[c]:
                    cd[b].add(c)
    out = {b: frozenset(s) for b, s in cd.items()}
    cfg._cd = out
    return out


def guard_blocks(cfg: CFG, block: int) -> FrozenSet[int]:
    """TRANSITIVE control dependence: every branch block whose outcome
    decides whether `block` executes — the divergence rule's guard set."""
    cd = control_deps(cfg)
    out: Set[int] = set()
    stack = [block]
    while stack:
        b = stack.pop()
        for c in cd[b]:
            if c not in out:
                out.add(c)
                stack.append(c)
    return frozenset(out)


# -- bounded path/sequence enumeration --------------------------------------

def back_edges(cfg: CFG) -> Set[Tuple[int, int]]:
    """DFS back-edges from the entry (loop-closing edges)."""
    cached = getattr(cfg, "_back", None)
    if cached is not None:
        return cached
    seen: Set[int] = set()
    on_stack: Set[int] = set()
    out: Set[Tuple[int, int]] = set()

    def dfs(b: int) -> None:
        seen.add(b)
        on_stack.add(b)
        for s in cfg.blocks[b].succs:
            if s in on_stack:
                out.add((b, s))
            elif s not in seen:
                dfs(s)
        on_stack.discard(b)

    import sys
    old = sys.getrecursionlimit()
    sys.setrecursionlimit(max(old, 4 * len(cfg.blocks) + 100))
    try:
        dfs(cfg.entry)
    finally:
        sys.setrecursionlimit(old)
    cfg._back = out
    return out


def emission_sequences(
    cfg: CFG,
    start: int,
    emit: Callable[[Block], Tuple],
    cap: int = 64,
) -> Optional[FrozenSet[Tuple]]:
    """The set of emission sequences along every path from `start` to a
    terminal block, over the back-edge-cut DAG (each loop body
    contributes its one-iteration sequence; the zero-iteration path goes
    through the loop header's exit edge). Returns None when the set
    exceeds `cap` — callers treat that as "too wide to judge" and stay
    silent rather than guessing."""
    cut = back_edges(cfg)
    memo: Dict[int, Optional[FrozenSet[Tuple]]] = {}

    def seqs(b: int) -> Optional[FrozenSet[Tuple]]:
        if b in memo:
            return memo[b]
        memo[b] = frozenset()  # cycle guard (shouldn't hit on the DAG)
        prefix = tuple(emit(cfg.blocks[b]))
        succs = [s for s in cfg.blocks[b].succs if (b, s) not in cut]
        if not succs:
            out: Optional[FrozenSet[Tuple]] = frozenset({prefix})
        else:
            acc: Set[Tuple] = set()
            out = None
            for s in succs:
                sub = seqs(s)
                if sub is None:
                    break
                acc.update(prefix + tail for tail in sub)
                if len(acc) > cap:
                    break
            else:
                out = frozenset(acc) if len(acc) <= cap else None
        memo[b] = out
        return out

    return seqs(start)
