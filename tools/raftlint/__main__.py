"""CLI: ``python -m tools.raftlint [--json] [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage error. Output is deterministic
(findings sorted by path/line/col/rule; ``--json`` additionally sorts
keys) so runs can be diffed and banked next to BENCH artifacts.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.raftlint.engine import (
    BASELINE_DEFAULT,
    Finding,
    lint_paths,
    load_baseline,
    registered_rules,
    write_baseline,
)
from tools.raftlint import rules as _rules  # noqa: F401  (registers rules)

DEFAULT_PATHS = ("raft_tpu", "bench", "tests", "tools")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.raftlint",
        description="AST-based static analysis for raft_tpu invariants "
                    "(trace safety, lock discipline, fault-site drift, "
                    "layer purity, hygiene). See docs/linting.md.",
    )
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                    help=f"files/directories to lint (default: "
                         f"{' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (stable key and finding "
                         "order, diffable across runs)")
    ap.add_argument("--baseline", default=BASELINE_DEFAULT, metavar="FILE",
                    help="baseline file of grandfathered findings "
                         "(default: tools/raftlint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report every finding)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from the current "
                         "PRAGMA-FILTERED findings and exit 0")
    ap.add_argument("--rules", metavar="RULE[,RULE...]",
                    help="run only the named rules")
    ap.add_argument("--root", metavar="DIR", default=None,
                    help="repo root for path scoping (default: the repo "
                         "containing tools/raftlint)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.write_baseline and args.rules:
        # a rule-filtered run sees only a slice of the findings; writing
        # it wholesale would silently discard every other rule's
        # grandfathered entries
        print("raftlint: --write-baseline cannot be combined with --rules "
              "(it would clobber other rules' baseline entries)",
              file=sys.stderr)
        return 2

    if args.list_rules:
        for r in registered_rules():
            kind = "project" if r.project else "module"
            print(f"{r.name:22} [{kind:7}] scope: {r.scope}\n"
                  f"{'':22} {r.summary}")
        return 0

    try:
        result = lint_paths(
            args.paths,
            repo_root=args.root,
            baseline=None if args.no_baseline else args.baseline,
            rules=args.rules.split(",") if args.rules else None,
        )
    except ValueError as e:
        print(f"raftlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        # pragma-filtered but not baseline-filtered: the new baseline is
        # exactly what would fail without one
        kept = [f for f in result.findings]
        if result.baseline_suppressed:
            # re-run without baseline so previously-baselined findings
            # stay grandfathered instead of silently dropping out
            kept = lint_paths(args.paths, repo_root=args.root,
                              baseline=None).findings
        # a path-subset run sees only a slice of the repo: preserve
        # existing entries for files outside the scan instead of
        # clobbering them
        preserved = [
            Finding(p, 0, 0, rule, msg)
            for (p, rule, msg), n in sorted(load_baseline(args.baseline).items())
            if not result.covers(p)
            for _ in range(n)
        ]
        write_baseline(args.baseline, kept + preserved)
        print(f"raftlint: wrote {len(kept)} finding(s) "
              f"({len(preserved)} preserved for unscanned paths) "
              f"to {args.baseline}")
        return 0

    if args.json:
        payload = {
            "findings": [f.to_dict() for f in result.findings],
            "pragma_suppressed": result.pragma_suppressed,
            "baseline_suppressed": result.baseline_suppressed,
            "stale_baseline": [list(k) for k in result.stale_baseline],
        }
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        for f in result.findings:
            print(f.format())
        for key in result.stale_baseline:
            print(f"raftlint: stale baseline entry (already fixed — remove "
                  f"it): {key[0]}: {key[1]}: {key[2]}", file=sys.stderr)
        n = len(result.findings)
        print(f"raftlint: {n} finding(s)"
              f" ({result.pragma_suppressed} pragma-suppressed,"
              f" {result.baseline_suppressed} baselined)",
              file=sys.stderr)
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
