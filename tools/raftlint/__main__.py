"""CLI: ``python -m tools.raftlint [--json] [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage error. Output is deterministic
(findings sorted by path/line/col/rule; ``--json`` additionally sorts
keys) so runs can be diffed and banked next to BENCH artifacts.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from tools.raftlint.engine import (
    BASELINE_DEFAULT,
    Finding,
    family_seconds,
    lint_paths,
    load_baseline,
    registered_rules,
    write_baseline,
)
from tools.raftlint import rules as _rules  # noqa: F401  (registers rules)

DEFAULT_PATHS = ("raft_tpu", "bench", "tests", "tools")


def _git(repo_root: str, *args: str) -> subprocess.CompletedProcess:
    return subprocess.run(["git", "-C", repo_root, *args],
                          capture_output=True, text=True)


def changed_files(repo_root: str, base: str = "auto") -> list:
    """Repo-relative .py files differing from the merge-base with `base`
    (default: the first of origin/main, origin/master, main, master that
    exists, else HEAD), PLUS uncommitted working-tree changes and
    untracked files — the full "what this PR touches" set, so
    ``--changed`` lints exactly what review will see. Deleted files are
    dropped (nothing to lint). Raises ValueError outside a git repo."""
    if _git(repo_root, "rev-parse", "--git-dir").returncode != 0:
        raise ValueError(f"--changed needs a git repository at {repo_root}")
    if base == "auto":
        base = next(
            (c for c in ("origin/main", "origin/master", "main", "master")
             if _git(repo_root, "rev-parse", "--verify", "-q",
                     c).returncode == 0),
            "HEAD")
    elif _git(repo_root, "rev-parse", "--verify", "-q",
              base).returncode != 0:
        # a typo'd base must fail loudly: silently anchoring at HEAD
        # would skip all committed drift while exiting green (the exact
        # failure mode iter_py_files polices for paths)
        raise ValueError(f"--changed base ref {base!r} does not resolve "
                         f"(did a path argument land in BASE position?)")
    mb = _git(repo_root, "merge-base", "HEAD", base)
    anchor = mb.stdout.strip() if mb.returncode == 0 else "HEAD"
    names = set()
    for args in (("diff", "--name-only", anchor, "HEAD"),  # committed drift
                 ("diff", "--name-only", "HEAD"),          # staged+unstaged
                 ("ls-files", "--others", "--exclude-standard")):  # untracked
        r = _git(repo_root, *args)
        if r.returncode == 0:
            names.update(n for n in r.stdout.splitlines() if n)
    return sorted(
        n for n in names
        if n.endswith(".py") and os.path.exists(os.path.join(repo_root, n)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.raftlint",
        description="AST-based static analysis for raft_tpu invariants "
                    "(trace safety, lock discipline, fault-site drift, "
                    "layer purity, hygiene, SPMD collective flow, "
                    "Pallas kernel/envelope consistency, the tuned-key "
                    "registry, cache-key completeness, the checkpoint "
                    "schema registry, and whole-program thread/race "
                    "analysis via the THREAD_ROOTS registry). See "
                    "docs/linting.md.",
    )
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                    help=f"files/directories to lint (default: "
                         f"{' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (stable key and finding "
                         "order, diffable across runs)")
    ap.add_argument("--baseline", default=BASELINE_DEFAULT, metavar="FILE",
                    help="baseline file of grandfathered findings "
                         "(default: tools/raftlint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report every finding)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from the current "
                         "PRAGMA-FILTERED findings and exit 0")
    ap.add_argument("--rules", metavar="RULE[,RULE...]",
                    help="run only the named rules")
    ap.add_argument("--root", metavar="DIR", default=None,
                    help="repo root for path scoping (default: the repo "
                         "containing tools/raftlint)")
    ap.add_argument("--changed", nargs="?", const="auto", default=None,
                    metavar="BASE",
                    help="lint only .py files differing from the "
                         "merge-base with BASE (default: origin/main or "
                         "main), plus uncommitted/untracked changes — "
                         "scoped to the given paths")
    ap.add_argument("--stats", action="store_true",
                    help="print per-rule-family wall times to stderr "
                         "(never stdout: --json byte-determinism is a "
                         "contract) — the CI lint tier archives these so "
                         "the <30 s wall gate stays diagnosable per engine")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.write_baseline and args.rules:
        # a rule-filtered run sees only a slice of the findings; writing
        # it wholesale would silently discard every other rule's
        # grandfathered entries
        print("raftlint: --write-baseline cannot be combined with --rules "
              "(it would clobber other rules' baseline entries)",
              file=sys.stderr)
        return 2

    if args.list_rules:
        for r in registered_rules():
            kind = "project" if r.project else "module"
            print(f"{r.name:22} [{kind:7}] scope: {r.scope}\n"
                  f"{'':22} {r.summary}")
        return 0

    paths = list(args.paths)
    if args.changed is not None:
        import tools.raftlint.engine as _engine

        root = args.root or os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(_engine.__file__))))
        try:
            scopes = tuple(
                (os.path.relpath(p, root) if os.path.isabs(p) else p)
                .replace(os.sep, "/").rstrip("/")
                for p in paths)
            paths = [
                f for f in changed_files(root, args.changed)
                if any(s in (".", "") or f == s or f.startswith(s + "/")
                       for s in scopes)
            ]
        except ValueError as e:
            print(f"raftlint: {e}", file=sys.stderr)
            return 2
        if not paths:
            print("raftlint: no changed Python files under "
                  f"{' '.join(args.paths)} — nothing to lint",
                  file=sys.stderr)
            return 0
        # narrowing is per FILE, not per rule: project rules analyze
        # only the changed files, so cross-file findings (a collective
        # reached through an unchanged callee, the far edge of a lock
        # cycle) can under-report here — CI always lints the full tree
        print(f"raftlint: --changed mode, linting {len(paths)} file(s); "
              "cross-file rules see only these files (CI runs the full "
              "tree)", file=sys.stderr)

    try:
        result = lint_paths(
            paths,
            repo_root=args.root,
            baseline=None if args.no_baseline else args.baseline,
            rules=args.rules.split(",") if args.rules else None,
        )
    except ValueError as e:
        print(f"raftlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        # pragma-filtered but not baseline-filtered: the new baseline is
        # exactly what would fail without one
        kept = [f for f in result.findings]
        if result.baseline_suppressed:
            # re-run without baseline so previously-baselined findings
            # stay grandfathered instead of silently dropping out
            kept = lint_paths(paths, repo_root=args.root,
                              baseline=None).findings
        # a path-subset run sees only a slice of the repo: preserve
        # existing entries for files outside the scan instead of
        # clobbering them
        preserved = [
            Finding(p, 0, 0, rule, msg)
            for (p, rule, msg), n in sorted(load_baseline(args.baseline).items())
            if not result.covers(p)
            for _ in range(n)
        ]
        write_baseline(args.baseline, kept + preserved)
        print(f"raftlint: wrote {len(kept)} finding(s) "
              f"({len(preserved)} preserved for unscanned paths) "
              f"to {args.baseline}")
        return 0

    if args.stats:
        total = sum(result.rule_seconds.values())
        for fam, (n, secs) in sorted(family_seconds(result.rule_seconds).items()):
            print(f"raftlint: stats: family={fam} rules={n} "
                  f"wall={secs:.2f}s", file=sys.stderr)
        print(f"raftlint: stats: total rules wall={total:.2f}s",
              file=sys.stderr)

    if args.json:
        payload = {
            "findings": [f.to_dict() for f in result.findings],
            "pragma_suppressed": result.pragma_suppressed,
            "baseline_suppressed": result.baseline_suppressed,
            "stale_baseline": [list(k) for k in result.stale_baseline],
        }
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        for f in result.findings:
            print(f.format())
        for key in result.stale_baseline:
            print(f"raftlint: stale baseline entry (already fixed — remove "
                  f"it): {key[0]}: {key[1]}: {key[2]}", file=sys.stderr)
        n = len(result.findings)
        print(f"raftlint: {n} finding(s)"
              f" ({result.pragma_suppressed} pragma-suppressed,"
              f" {result.baseline_suppressed} baselined)",
              file=sys.stderr)
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
