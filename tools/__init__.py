"""Developer tooling that ships with the repo (not part of the
``raft_tpu`` runtime package). Currently: ``tools.raftlint``, the
AST-based static-analysis suite run by CI (``python -m tools.raftlint``).
"""
