"""schedfuzz: deterministic interleaving fuzzer for raft_tpu's
serve/mutation/integrity concurrency. See scheduler.py for the model;
tests/test_schedfuzz.py for the pinned ordering drills; docs/linting.md
for how threadcheck findings pair with schedfuzz schedules."""

from tools.schedfuzz.scheduler import (  # noqa: F401
    DEFAULT_MAX_STEPS,
    CoopCondition,
    CoopEvent,
    CoopLock,
    CoopRLock,
    DeadlockError,
    ScheduleLimitError,
    Scheduler,
    find_failure,
    instrumented,
    preemption_sweep,
    yield_point,
)
