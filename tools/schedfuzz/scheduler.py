"""schedfuzz: a cooperative deterministic scheduler for racing real code.

raftlint's threadcheck (tools/raftlint/threads.py) proves race findings
statically; this module makes them *reproducible*. All threads of a
scenario are serialized onto one seeded controller: every managed
thread runs exclusively until it reaches a scheduling point (a
``yield_point()`` mark, or any acquire/release/wait on an instrumented
synchronization primitive), hands control back, and the controller —
and only the controller — picks who runs next. The pick sequence is a
pure function of the seed, so a schedule that loses a flight-recorder
dump or tears a half-published index is a *regression test*, not a
flake: same seed, byte-identical trace, same failure.

Two exploration modes:

  * seeded permutations — ``Scheduler(seed=k)`` draws every scheduling
    decision from ``random.Random(k)``;
  * preemption sweeps — ``preemption_sweep``/``find_failure`` re-run a
    scenario once per decision index with a forced context switch at
    that index, the "preempt at every yield point once" pass that
    flushes out windows a random walk misses.

``instrumented(sched)`` monkeypatches ``threading.Lock/RLock/
Condition/Event`` (and optionally ``Thread``) so *production* code
constructed inside the block cooperates without modification. Locks
created before the block (module-level locks bound at import) stay
real: they contain no scheduling points, so under schedfuzz they are
atomic sections — they cannot deadlock the controller, they just hide
interleavings inside themselves.

Determinism contract: traces contain step counters, task names, and
sequential primitive names ("lock1", "cond2") — never object ids,
wall-clock times, or thread idents. Timed waits expire on a virtual
clock: when nothing is runnable, the earliest ``(deadline, name)``
sleeper wakes with a timeout, deterministically. Untimed blocking with
nothing runnable raises ``DeadlockError`` with the full wait graph.

This package is test infrastructure: it never imports raft_tpu, and
``yield_point()`` is a no-op when no scheduler manages the calling
thread, so drill helpers can call it unconditionally.
"""

from __future__ import annotations

import collections
import contextlib
import random
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# real primitives, captured before any instrumented() block can patch
# the module: the controller's own handshake must never cooperate
_REAL_THREAD = threading.Thread
_REAL_EVENT = threading.Event
_real_get_ident = threading.get_ident
_REAL_FACTORIES = {name: getattr(threading, name)
                   for name in ("Lock", "RLock", "Condition", "Event")}


@contextlib.contextmanager
def _real_primitives():
    """Pin the real factories for the duration: Thread.__init__/start
    build their _started Event from the *module globals* of threading,
    so spawning a real controller thread while instrumented() is active
    would otherwise hand the interpreter a coop Event to park on."""
    saved = {k: getattr(threading, k) for k in _REAL_FACTORIES}
    for k, v in _REAL_FACTORIES.items():
        setattr(threading, k, v)
    try:
        yield
    finally:
        for k, v in saved.items():
            setattr(threading, k, v)

#: real-thread-ident -> (Scheduler, _Task) for every *managed* thread;
#: yield_point() and the coop primitives look the caller up here, and
#: an unmanaged caller (controller, plain pytest thread) falls through
#: to non-cooperative behavior
_TASKS: Dict[int, Tuple["Scheduler", "_Task"]] = {}

DEFAULT_MAX_STEPS = 20000

#: ownership token for coop-lock use from unmanaged threads (scenario
#: setup on the controller thread before run()): the lock must read as
#: held, but there is no _Task to own it
_FOREIGN = object()


class DeadlockError(RuntimeError):
    """Every live task is blocked without a timeout: the schedule
    cannot make progress. The message carries the wait graph."""


class ScheduleLimitError(RuntimeError):
    """The scenario exceeded max_steps scheduling points (livelock, or
    a scenario that genuinely needs a larger budget)."""


class _Kill(BaseException):
    """Raised inside an abandoned task thread so it unwinds instead of
    parking forever on its gate (run() teardown). BaseException so
    scenario code's ``except Exception`` cannot swallow it."""


class _Task:
    __slots__ = ("name", "gate", "done", "blocked_on", "deadline",
                 "timed_out", "stop", "exc", "thread")

    def __init__(self, name: str):
        self.name = name
        self.gate = _REAL_EVENT()
        self.done = False
        self.blocked_on = None   # waitable with _ready(task), or None
        self.deadline: Optional[float] = None  # virtual-clock absolute
        self.timed_out = False
        self.stop = False
        self.exc: Optional[BaseException] = None
        self.thread: Optional[threading.Thread] = None


class Scheduler:
    """One seeded controller serializing N managed threads.

    Usage::

        sched = Scheduler(seed=7)
        with instrumented(sched):
            rec = FlightRecorder()          # its locks cooperate
        sched.spawn(writer, name="writer")
        sched.spawn(reader, name="reader")
        sched.run()                          # raises what the tasks raised
        assert sched.trace == expected       # byte-stable per seed

    ``preempt_at=i`` forces the i-th scheduling decision to switch away
    from the previously-running task (when another is runnable) — the
    building block of the preemption sweep. ``sequential=True`` replaces
    the random walk with run-to-block scheduling (the running task keeps
    the processor until it blocks or finishes): combined with
    ``preempt_at`` this is the classic "preempt at every yield point
    once" pass, which exposes tears that need one long exclusive
    stretch — windows a random walk rarely lines up.
    """

    def __init__(self, seed: int = 0, preempt_at: Optional[int] = None,
                 max_steps: int = DEFAULT_MAX_STEPS,
                 sequential: bool = False):
        self._rng = random.Random(int(seed))
        self._preempt_at = preempt_at
        self._sequential = bool(sequential)
        self._max_steps = int(max_steps)
        with _real_primitives():
            self._ctl = _REAL_EVENT()
        self._tasks: List[_Task] = []
        self._lines: List[str] = []
        self._counters: Dict[str, int] = collections.defaultdict(int)
        self._vt = 0.0            # virtual clock, advanced by expiry only
        self._decisions = 0
        self._ran = False

    # -- introspection ----------------------------------------------------

    @property
    def trace(self) -> str:
        """The schedule as text: one ``<step> <event>`` line per
        scheduling-relevant action. Byte-identical for identical
        (seed, preempt_at, scenario)."""
        return "\n".join(self._lines)

    @property
    def decisions(self) -> int:
        """Scheduling decisions taken by the last run() — the sweep
        range for forced preemption."""
        return self._decisions

    def next_name(self, kind: str) -> str:
        self._counters[kind] += 1
        return f"{kind}{self._counters[kind]}"

    def _trace(self, text: str) -> None:
        self._lines.append(f"{len(self._lines)} {text}")

    # -- task plumbing ----------------------------------------------------

    def spawn(self, fn: Callable, *args, name: Optional[str] = None,
              **kwargs) -> _Task:
        """Register ``fn`` as a managed thread. The real thread starts
        immediately but parks on its gate until the controller grants
        it; safe to call both before run() and from inside a managed
        task."""
        with _real_primitives():
            # the gate Event and the thread's _started internals must
            # both be built from REAL primitives even when spawn is
            # called inside an instrumented() block
            task = _Task(name or self.next_name("task"))
            self._tasks.append(task)
            self._trace(f"spawn {task.name}")
            t = _REAL_THREAD(target=self._bootstrap,
                             args=(task, fn, args, kwargs),
                             name=f"schedfuzz-{task.name}", daemon=True)
            task.thread = t
            t.start()
        return task

    def _bootstrap(self, task: _Task, fn, args, kwargs) -> None:
        _TASKS[_real_get_ident()] = (self, task)
        try:
            task.gate.wait()
            task.gate.clear()
            if task.stop:
                return
            try:
                fn(*args, **kwargs)
            except _Kill:
                return
            except BaseException as e:  # noqa: BLE001 — reported via run()
                task.exc = e
                self._trace(f"raise {task.name} {type(e).__name__}")
            else:
                self._trace(f"done {task.name}")
        finally:
            task.done = True
            _TASKS.pop(_real_get_ident(), None)
            self._ctl.set()

    def _current(self) -> Optional[_Task]:
        hit = _TASKS.get(_real_get_ident())
        return hit[1] if hit is not None and hit[0] is self else None

    def _switch(self, task: _Task) -> None:
        """Task side of the handshake: hand control to the controller,
        park until granted again."""
        if task.stop:
            # teardown already started (e.g. a finally-block release
            # while unwinding on _Kill): never park again
            raise _Kill()
        self._ctl.set()
        task.gate.wait()
        task.gate.clear()
        if task.stop:
            raise _Kill()

    def checkpoint(self, text: Optional[str] = None) -> None:
        """A voluntary scheduling point: the controller may switch here.
        No-op off-schedule."""
        task = self._current()
        if task is None:
            return
        if text:
            self._trace(text)
        self._switch(task)

    def block(self, waitable, text: str,
              timeout: Optional[float] = None) -> bool:
        """Park the calling task on ``waitable`` (anything with
        ``_ready(task)``) until the controller deems it ready — or, with
        a timeout, until the virtual clock expires it. Returns False on
        expiry. Off-schedule callers get an immediate ready-check
        instead (setup-phase use of coop primitives)."""
        task = self._current()
        if task is None:
            if not waitable._ready(None):
                raise DeadlockError(
                    f"unmanaged thread would block forever: {text}")
            return True
        task.blocked_on = waitable
        if timeout is not None:
            task.deadline = self._vt + max(0.0, float(timeout))
        self._trace(text)
        self._switch(task)
        task.blocked_on = None
        task.deadline = None
        timed_out, task.timed_out = task.timed_out, False
        return not timed_out

    # -- controller -------------------------------------------------------

    def run(self) -> "Scheduler":
        """Drive every spawned task to completion on the calling
        thread. Re-raises the first task exception (in schedule order)
        after teardown; raises DeadlockError / ScheduleLimitError on a
        stuck or runaway schedule."""
        self._ran = True
        last: Optional[_Task] = None
        steps = 0
        try:
            while True:
                live = [t for t in self._tasks if not t.done]
                if not live:
                    break
                runnable = [t for t in live
                            if t.blocked_on is None
                            or t.blocked_on._ready(t)]
                if not runnable:
                    timed = [t for t in live if t.deadline is not None]
                    if not timed:
                        raise DeadlockError(self._wait_graph(live))
                    t = min(timed, key=lambda x: (x.deadline, x.name))
                    self._vt = max(self._vt, t.deadline)
                    t.timed_out = True
                    expired = getattr(t.blocked_on, "_expire", None)
                    if expired is not None:
                        expired(t)
                    t.blocked_on = None
                    t.deadline = None
                    runnable = [t]
                i = self._decisions
                self._decisions += 1
                forced = (self._preempt_at is not None
                          and i == self._preempt_at
                          and len(runnable) > 1 and last in runnable)
                if forced:
                    # switch to the next runnable task after `last` in
                    # spawn order (deterministic, covers both directions
                    # across the sweep)
                    order = [x for x in self._tasks if x in runnable]
                    j = order.index(last)
                    t = order[(j + 1) % len(order)]
                    self._trace(f"preempt -> {t.name}")
                elif self._sequential:
                    t = last if last in runnable else runnable[0]
                else:
                    t = runnable[self._rng.randrange(len(runnable))]
                last = t
                t.gate.set()
                self._ctl.wait()
                self._ctl.clear()
                steps += 1
                if steps > self._max_steps:
                    raise ScheduleLimitError(
                        f"schedule exceeded {self._max_steps} steps "
                        "(livelock, or raise max_steps)")
        finally:
            # unwind abandoned threads so nothing parks past the test
            for t in self._tasks:
                if not t.done:
                    t.stop = True
                    t.gate.set()
            for t in self._tasks:
                if t.thread is not None:
                    t.thread.join(timeout=5.0)
        for t in self._tasks:
            if t.exc is not None:
                raise t.exc
        return self

    def _wait_graph(self, live: Sequence[_Task]) -> str:
        rows = []
        for t in sorted(live, key=lambda x: x.name):
            on = getattr(t.blocked_on, "_name", None) or "??"
            rows.append(f"{t.name} blocked on {on}")
        return "deadlock: " + "; ".join(rows)


# ---------------------------------------------------------------------------
# cooperative primitives (threading-API compatible)


class CoopLock:
    """threading.Lock under scheduler control: pure ownership
    bookkeeping, with a scheduling point at every acquire and
    release."""

    _reentrant = False

    def __init__(self, sched: Scheduler, name: Optional[str] = None):
        self._sched = sched
        self._name = name or sched.next_name(
            "rlock" if self._reentrant else "lock")
        self._owner: Optional[_Task] = None
        self._count = 0

    def _ready(self, task) -> bool:
        if self._owner is None:
            return True
        return self._reentrant and \
            self._owner is (task if task is not None else _FOREIGN)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sched = self._sched
        task = sched._current()
        sched.checkpoint()  # contended or not, acquisition is a window
        while not self._ready(task):
            if not blocking:
                return False
            ok = sched.block(
                self, f"block {task.name} {self._name}",
                timeout if timeout is not None and timeout >= 0 else None)
            if not ok:
                sched._trace(f"timeout {task.name} {self._name}")
                return False
        self._owner = task if task is not None else _FOREIGN
        self._count += 1
        if task is not None:
            sched._trace(f"acquire {task.name} {self._name}")
        return True

    def release(self) -> None:
        task = self._sched._current()
        holder = task if task is not None else _FOREIGN
        if self._owner is not holder or self._count <= 0:
            raise RuntimeError(f"release of un-acquired {self._name}")
        self._count -= 1
        if self._count == 0:
            self._owner = None
        if task is not None:
            self._sched._trace(f"release {task.name} {self._name}")
        self._sched.checkpoint()

    def locked(self) -> bool:
        return self._owner is not None

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition support (mirrors threading's private protocol)
    def _release_save(self):
        task = self._sched._current()
        if self._owner is not task or self._count <= 0:
            raise RuntimeError(f"wait on un-acquired {self._name}")
        saved = self._count
        self._count = 0
        self._owner = None
        return saved

    def _acquire_restore(self, saved) -> None:
        sched = self._sched
        task = sched._current()
        while not self._ready(task):
            sched.block(self, f"block {task.name} {self._name}")
        self._owner = task
        self._count = saved
        if task is not None:
            sched._trace(f"reacquire {task.name} {self._name}")


class CoopRLock(CoopLock):
    _reentrant = True


class CoopCondition:
    """threading.Condition over a coop lock. Deterministic FIFO
    notify; ``wait`` releases fully, blocks until notified (or virtual
    timeout), then reacquires."""

    def __init__(self, sched: Scheduler, lock=None,
                 name: Optional[str] = None):
        self._sched = sched
        self._name = name or sched.next_name("cond")
        self._lock = lock if lock is not None else CoopRLock(sched)
        self._waiters: List[_Task] = []
        self._notified: List[_Task] = []

    def _ready(self, task) -> bool:
        return task in self._notified

    def _expire(self, task) -> None:
        # virtual-clock expiry: drop the waiter before it re-runs
        if task in self._waiters:
            self._waiters.remove(task)

    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self) -> None:
        self._lock.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        sched = self._sched
        task = sched._current()
        saved = self._lock._release_save()
        if task is None:
            raise DeadlockError(
                f"unmanaged thread cannot wait on {self._name}")
        self._waiters.append(task)
        ok = sched.block(self, f"wait {task.name} {self._name}", timeout)
        if ok:
            self._notified.remove(task)
        else:
            sched._trace(f"timeout {task.name} {self._name}")
        self._lock._acquire_restore(saved)
        return ok

    def wait_for(self, predicate: Callable[[], bool],
                 timeout: Optional[float] = None) -> bool:
        result = predicate()
        endtime = None
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = self._sched._vt + timeout
                remaining = endtime - self._sched._vt
                if remaining <= 0:
                    break
                self.wait(remaining)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        task = self._sched._current()
        if self._lock._owner is not task or task is None and \
                self._lock._owner is not None:
            # mirror threading: notify requires the lock (unmanaged
            # setup-phase callers hold no coop ownership → allow)
            if task is not None:
                raise RuntimeError(f"notify on un-acquired {self._name}")
        moved = 0
        while self._waiters and moved < n:
            w = self._waiters.pop(0)
            self._notified.append(w)
            moved += 1
        if task is not None and moved:
            self._sched._trace(f"notify {task.name} {self._name} x{moved}")

    def notify_all(self) -> None:
        self.notify(len(self._waiters) or 1)


class CoopEvent:
    """threading.Event under scheduler control."""

    def __init__(self, sched: Scheduler, name: Optional[str] = None):
        self._sched = sched
        self._name = name or sched.next_name("event")
        self._flag = False

    def _ready(self, task) -> bool:
        return self._flag

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        self._flag = True
        task = self._sched._current()
        if task is not None:
            self._sched._trace(f"set {task.name} {self._name}")
            self._sched.checkpoint()

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        sched = self._sched
        task = sched._current()
        sched.checkpoint()
        if self._flag:
            return True
        if task is None:
            if timeout is not None:
                return self._flag
            raise DeadlockError(
                f"unmanaged thread would block forever on {self._name}")
        ok = sched.block(self, f"wait {task.name} {self._name}", timeout)
        if not ok:
            sched._trace(f"timeout {task.name} {self._name}")
        return self._flag


class _JoinTarget:
    def __init__(self, task: _Task):
        self._task = task
        self._name = f"join:{task.name}"

    def _ready(self, task) -> bool:
        return self._task.done


def _coop_thread_factory(sched: Scheduler):
    """A threading.Thread stand-in whose start() registers with the
    scheduler instead of running free."""

    class CoopThread:
        def __init__(self, group=None, target=None, name=None, args=(),
                     kwargs=None, *, daemon=None):
            self._target = target
            self._args = args
            self._kwargs = kwargs or {}
            self.name = name or sched.next_name("thread")
            self.daemon = daemon
            self._task: Optional[_Task] = None

        def run(self):
            if self._target is not None:
                self._target(*self._args, **self._kwargs)

        def start(self):
            if self._task is not None:
                raise RuntimeError("threads can only be started once")
            self._task = sched.spawn(self.run, name=self.name)

        def join(self, timeout: Optional[float] = None):
            if self._task is None:
                raise RuntimeError("cannot join thread before it is started")
            t = sched._current()
            if t is None:
                return  # controller-side join: run() already drives it
            sched.block(_JoinTarget(self._task),
                        f"join {t.name} {self._task.name}", timeout)

        def is_alive(self) -> bool:
            return self._task is not None and not self._task.done

    return CoopThread


# ---------------------------------------------------------------------------
# instrumentation + exploration helpers


def yield_point(tag: str = "") -> None:
    """Mark an interleaving-relevant program point. Under a scheduler
    this is a scheduling decision; everywhere else it is a no-op, so
    drill helpers and scenario bodies can call it unconditionally."""
    hit = _TASKS.get(_real_get_ident())
    if hit is None:
        return
    sched, task = hit
    sched._trace(f"yield {task.name}" + (f" {tag}" if tag else ""))
    sched._switch(task)


@contextlib.contextmanager
def instrumented(sched: Scheduler, patch_thread: bool = True):
    """Patch threading's primitive factories so code constructed inside
    the block cooperates with ``sched``. Locks created *before* the
    block stay real — they become atomic sections, not deadlocks,
    because no scheduling point can occur while one is held."""
    names = ["Lock", "RLock", "Condition", "Event"]
    if patch_thread:
        names.append("Thread")
    saved = {k: getattr(threading, k) for k in names}
    threading.Lock = lambda: CoopLock(sched)
    threading.RLock = lambda: CoopRLock(sched)
    threading.Condition = lambda lock=None: CoopCondition(sched, lock)
    threading.Event = lambda: CoopEvent(sched)
    if patch_thread:
        threading.Thread = _coop_thread_factory(sched)
    try:
        yield sched
    finally:
        for k, v in saved.items():
            setattr(threading, k, v)


def preemption_sweep(scenario: Callable[[Scheduler], None], seed: int = 0,
                     limit: int = 256) -> List[Tuple[Optional[int], str]]:
    """The "preempt at every yield point once" pass: run ``scenario``
    under the sequential (run-to-block) baseline, then once per decision
    index with a forced preemption there — each swept schedule is one
    long exclusive stretch broken at exactly one point, the shape that
    exposes half-published state. Returns
    ``[(preempt_at_or_None, trace), ...]``; exceptions propagate from
    the run that hit them (with its schedule already banked in the
    scheduler the caller built). ``seed`` only matters if the scenario
    itself draws on it: sequential scheduling consumes no randomness."""
    base = Scheduler(seed, sequential=True)
    scenario(base)
    base.run()
    out: List[Tuple[Optional[int], str]] = [(None, base.trace)]
    for i in range(min(base.decisions, limit)):
        s = Scheduler(seed, preempt_at=i, sequential=True)
        scenario(s)
        s.run()
        out.append((i, s.trace))
    return out


def find_failure(scenario: Callable[[Scheduler], None],
                 seeds: Sequence[int] = (0, 1, 2, 3),
                 sweep_limit: int = 64):
    """Hunt for an interleaving that makes ``scenario`` raise: seeded
    random walks first, then the sequential preempt-once sweep. Returns
    ``(exception, trace, label)`` for the first failing schedule, or
    None if every explored schedule passes — the shape both directions
    of a race regression test need (pre-fix: not None; post-fix:
    None)."""
    probes: List[Tuple[str, Scheduler]] = \
        [(f"seed={s}", Scheduler(s)) for s in seeds]
    probes.append(("sequential", Scheduler(0, sequential=True)))
    for label, sched in probes:
        try:
            scenario(sched)
            sched.run()
        except (DeadlockError, ScheduleLimitError):
            raise
        except Exception as e:  # noqa: BLE001 — the hunt's quarry
            return e, sched.trace, label
    n = probes[-1][1].decisions
    for i in range(min(n, sweep_limit)):
        s = Scheduler(0, preempt_at=i, sequential=True)
        try:
            scenario(s)
            s.run()
        except (DeadlockError, ScheduleLimitError):
            raise
        except Exception as e:  # noqa: BLE001
            return e, s.trace, f"preempt_at={i}"
    return None
