"""perfgate — the bench-ledger regression watchdog.

Run as ``python -m tools.perfgate [--json] [--enforce]``. Reads the
append-only BENCH_LEDGER.jsonl that `bench/common.Banker` feeds (one
entry per banked row, stamped with git SHA + platform), groups rows by
(bench, platform, metric), and compares the freshest SHA's values
against a rolling baseline (median of the last `--window` rows from
OLDER SHAs in the same group) with per-unit tolerance bands.

raftlint-style discipline: stdlib only, never imports raft_tpu (the
gate must run even when the library is broken), deterministic output —
two runs over the same ledger produce byte-identical ``--json`` (the CI
acceptance check literally `cmp`s them).

Modes:
  report-only (default): findings printed, exit 0 — CI visibility
    without blocking; every PR sees drift the moment it lands.
  --enforce: exit 1 when any regression finding survives — the flip to
    a hard gate is one flag once the trajectory has enough history.

Honesty: rows are only ever compared within the same platform group, so
a CPU-fallback row can never "regress" against a chip row (or
vice-versa — the 5,315-QPS chip headline is not a baseline for a CPU
rehearsal). `no_baseline` findings mark metrics with no comparable
history; they are informational, never failures.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

#: units where larger is better; everything else (latencies, seconds,
#: ms) regresses when it grows
HIGHER_BETTER = {"qps", "req/s", "items/s", "recall", "mfu"}

#: relative tolerance band per unit class (fraction of the baseline);
#: timings/throughputs are noisy on shared hosts, recall is not
TOLERANCES: Dict[str, float] = {
    "qps": 0.20, "req/s": 0.20, "items/s": 0.20,
    "ms": 0.20, "s": 0.20,
    "recall": 0.01,
    "mfu": 0.25,
}
DEFAULT_TOLERANCE = 0.20
DEFAULT_WINDOW = 8

_UNIT_ALIASES = {"seconds": "s", "sec": "s"}


def _canon_unit(unit: str) -> str:
    u = str(unit).lower()
    return _UNIT_ALIASES.get(u, u)


def read_ledger(path: str) -> List[dict]:
    """Parseable entries in file order; torn lines skipped (same
    discipline as raft_tpu.obs.ledger.read, re-implemented here because
    perfgate must not import the library it gates)."""
    rows: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(entry, dict) and isinstance(entry.get("row"), dict):
                    rows.append(entry)
    except OSError:
        return []
    return rows


def extract_metrics(entry: dict) -> List[Tuple[str, float, str]]:
    """(metric, value, unit) triples from one ledger entry's row.

    The headline `value`/`unit` pair becomes the row's base metric
    (named by its case/metric field); well-known named fields (qps,
    p50_ms, p99_ms, seconds, recall) become `<base>:<field>` metrics so
    e.g. a p99 regression is gated independently of throughput.
    """
    row = entry["row"]
    base = row.get("case") or row.get("metric") or "value"
    if row.get("engine"):
        base = f"{base}/{row['engine']}"
    out: List[Tuple[str, float, str]] = []
    if isinstance(row.get("value"), (int, float)) and row.get("unit"):
        out.append((str(base), float(row["value"]), _canon_unit(row["unit"])))
    named = (("qps", "qps"), ("p50_ms", "ms"), ("p99_ms", "ms"),
             ("seconds", "s"), ("build_seconds", "s"), ("recall", "recall"),
             ("recall@10", "recall"),  # bench.py headline rows spell it this way
             ("mfu", "mfu"))
    for field, unit in named:
        val = row.get(field)
        if isinstance(val, (int, float)):
            out.append((f"{base}:{field}", float(val), unit))
    return out


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def evaluate(entries: List[dict], window: int = DEFAULT_WINDOW,
             fresh_sha: Optional[str] = None) -> dict:
    """Compare the freshest SHA's rows against each group's rolling
    baseline. Returns the deterministic findings document the CLI
    emits."""
    if not entries:
        return {"fresh_sha": None, "checked": 0, "findings": [],
                "regressions": 0, "no_baseline": 0}
    sha = fresh_sha if fresh_sha is not None else entries[-1].get("sha")
    # group: (bench, platform, metric) -> ordered [(sha, value, unit)]
    groups: Dict[Tuple[str, str, str], List[Tuple[str, float, str]]] = {}
    for entry in entries:
        for metric, value, unit in extract_metrics(entry):
            key = (str(entry.get("bench", "?")),
                   str(entry.get("platform", "?")), metric)
            groups.setdefault(key, []).append(
                (str(entry.get("sha")), value, unit))
    findings = []
    for (bench, platform, metric), rows in sorted(groups.items()):
        fresh = [v for s, v, _ in rows if s == sha]
        if not fresh:
            continue  # group with no fresh rows: nothing to gate
        unit = rows[-1][2]
        baseline_pool = [v for s, v, _ in rows if s != sha][-int(window):]
        finding = {
            "bench": bench, "platform": platform, "metric": metric,
            "unit": unit, "fresh": round(fresh[-1], 6),
            "n_fresh": len(fresh), "n_baseline": len(baseline_pool),
        }
        if not baseline_pool:
            finding.update(baseline=None, ratio=None, status="no_baseline")
            findings.append(finding)
            continue
        baseline = _median(baseline_pool)
        tol = TOLERANCES.get(unit, DEFAULT_TOLERANCE)
        ratio = (fresh[-1] / baseline) if baseline else None
        finding["baseline"] = round(baseline, 6)
        finding["ratio"] = round(ratio, 4) if ratio is not None else None
        if ratio is None:
            status = "no_baseline"
        elif unit in HIGHER_BETTER:
            status = ("regression" if ratio < 1.0 - tol
                      else "improved" if ratio > 1.0 + tol else "ok")
        else:
            status = ("regression" if ratio > 1.0 + tol
                      else "improved" if ratio < 1.0 - tol else "ok")
        finding["status"] = status
        findings.append(finding)
    return {
        "fresh_sha": sha,
        "checked": len(findings),
        "findings": findings,
        "regressions": sum(1 for f in findings if f["status"] == "regression"),
        "no_baseline": sum(1 for f in findings
                           if f["status"] == "no_baseline"),
    }


def render_text(doc: dict, ledger_name: str) -> str:
    lines = [f"perfgate: {ledger_name} @ {doc['fresh_sha'] or 'empty'} — "
             f"{doc['checked']} metrics checked, "
             f"{doc['regressions']} regression(s), "
             f"{doc['no_baseline']} without baseline"]
    for f in doc["findings"]:
        if f["status"] == "ok":
            continue
        base = "-" if f["baseline"] is None else f"{f['baseline']:g}"
        ratio = "-" if f["ratio"] is None else f"{f['ratio']:.3f}x"
        lines.append(
            f"  [{f['status']:<11s}] {f['bench']} ({f['platform']}) "
            f"{f['metric']}: {f['fresh']:g} {f['unit']} "
            f"(baseline {base}, {ratio})")
    return "\n".join(lines) + "\n"


def default_ledger_path() -> str:
    env = os.environ.get("RAFT_TPU_BENCH_LEDGER", "").strip()
    if env:
        return env
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "BENCH_LEDGER.jsonl")
