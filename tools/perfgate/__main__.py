"""CLI: python -m tools.perfgate [--ledger PATH] [--json] [--window N]
[--enforce]."""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.perfgate import (
    DEFAULT_WINDOW,
    default_ledger_path,
    evaluate,
    read_ledger,
    render_text,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.perfgate",
        description="Gate the freshest bench-ledger rows against a "
                    "rolling per-(bench, platform, metric) baseline. "
                    "Report-only by default; --enforce exits 1 on "
                    "regressions.",
    )
    parser.add_argument("--ledger", default=None,
                        help="ledger path (default: RAFT_TPU_BENCH_LEDGER "
                             "or the repo BENCH_LEDGER.jsonl)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings (deterministic: "
                             "identical ledgers produce identical bytes)")
    parser.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                        help="baseline pool size per metric group")
    parser.add_argument("--fresh-sha", default=None,
                        help="gate this SHA's rows (default: the SHA of "
                             "the last ledger line)")
    parser.add_argument("--enforce", action="store_true",
                        help="exit 1 when regressions are found "
                             "(default: report-only, always exit 0)")
    args = parser.parse_args(argv)

    path = args.ledger or default_ledger_path()
    entries = read_ledger(path)
    doc = evaluate(entries, window=args.window, fresh_sha=args.fresh_sha)
    # the ledger is named by basename only: --json output is committed /
    # diffed in CI and absolute temp paths would break determinism
    doc["ledger"] = os.path.basename(path)
    if args.json:
        sys.stdout.write(json.dumps(doc, indent=1, sort_keys=True) + "\n")
    else:
        sys.stdout.write(render_text(doc, doc["ledger"]))
    if args.enforce and doc["regressions"]:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
